# Developer entry points. `make lint` is the one-command gate PR
# builders run locally; tier-1 runs the same check as a test
# (tests/test_raycheck.py::TestLiveTree).

PYTHON ?= python3

.PHONY: lint test build asan tsan clean obs-dump

lint:
	$(PYTHON) -m tools.raycheck ray_tpu/ tests/

# merge a run's flight-recorder shards into one Perfetto/Chrome trace:
#   make obs-dump DIR=/tmp/ray_tpu_debug/gcs-<addr>
DIR ?= $(firstword $(wildcard /tmp/ray_tpu_debug/*))
obs-dump:
	$(PYTHON) -m tools.obsdump $(DIR)

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

build:
	$(MAKE) -C src/fastpath PYTHON=$(PYTHON)
	$(MAKE) -C src/object_store

# instrumented native extensions, built into separate _build_asan dirs —
# NEVER into the production _build, where an ASan .so (unloadable without
# LD_PRELOAD) would silently force the Python fallback on every later run.
# See README "Static analysis & sanitizers" for the LD_PRELOAD recipe.
ASAN_FASTPATH_DIR := $(CURDIR)/ray_tpu/_private/fastpath/_build_asan
ASAN_STORE_DIR := $(CURDIR)/ray_tpu/_private/object_store/_build_asan

asan:
	$(MAKE) -C src/fastpath SANITIZE=asan PYTHON=$(PYTHON) BUILD_DIR=$(ASAN_FASTPATH_DIR)
	$(MAKE) -C src/object_store SANITIZE=asan BUILD_DIR=$(ASAN_STORE_DIR)
	@echo "ASan fastpath: run with RAY_TPU_FASTPATH_BUILD_DIR=$(ASAN_FASTPATH_DIR)"

TSAN_FASTPATH_DIR := $(CURDIR)/ray_tpu/_private/fastpath/_build_tsan
TSAN_STORE_DIR := $(CURDIR)/ray_tpu/_private/object_store/_build_tsan

tsan:
	$(MAKE) -C src/fastpath SANITIZE=tsan PYTHON=$(PYTHON) BUILD_DIR=$(TSAN_FASTPATH_DIR)
	$(MAKE) -C src/object_store SANITIZE=tsan BUILD_DIR=$(TSAN_STORE_DIR)
	@echo "TSan fastpath: run with RAY_TPU_FASTPATH_BUILD_DIR=$(TSAN_FASTPATH_DIR)"

clean:
	$(MAKE) -C src/fastpath clean
	$(MAKE) -C src/object_store clean
	rm -rf $(ASAN_FASTPATH_DIR) $(ASAN_STORE_DIR) $(TSAN_FASTPATH_DIR) $(TSAN_STORE_DIR)
