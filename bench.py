"""Benchmark of record: flagship Llama-family LoRA train step, tokens/sec/chip,
plus the second metric of record — `ray.util.collective` allreduce GB/s —
and the control-plane microbenchmark suite (ray_perf ops/s).

Matches BASELINE.json's metrics ("Ray Train Llama tokens/sec/chip;
ray.util.collective allreduce GB/s"); ``vs_baseline`` on the headline
line is MFU / 0.35 — the reference's north-star target is >=35% MFU on
the Llama LoRA fine-tune (BASELINE.md).

Output contract: secondary metrics print as `# `-prefixed compact JSON
comments (recorded in the driver's BENCH tail) and the FULL results are
written to MICROBENCH.json at the repo root; the LAST stdout line is the
single headline JSON line {"metric", "value", "unit", "vs_baseline"}.

Robustness contract (VERDICT round 1, item 1): the TPU tunnel backend can be
transiently unavailable, and a bare ``jax.devices()`` crash means no perf
number at all. So the parent process runs each measurement in a CHILD
process: try the TPU backend (with retries), then fall back to a CPU run —
whichever child first emits a benchmark JSON line wins and the parent
re-prints it. A headline JSON line is ALWAYS produced.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


# Peak bf16 FLOP/s per chip (public spec sheets).
PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5e": 197e12, "v5p": 459e12, "v6e": 918e12, "v6p": 4614e12 / 2,
}

_CHILD_ENV = "RAY_TPU_BENCH_CHILD"


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind.replace(" ", "").replace("tpu", ""):
            return val
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU — MFU not meaningful, still report


def _run_probe() -> None:
    """Child-process body: quick TPU viability check — backend init plus a
    tiny compiled matmul. Bounds time-to-first-number: a hanging tunnel
    backend costs one short probe timeout, not a full benchmark timeout.

    A failure prints ``PROBE_ERR <ExcClass>: <message>`` on stdout so
    the parent can RECORD the diagnosis (``tpu_probe_error`` in
    MICROBENCH.json) instead of the old silent ``tpu_probe: failed`` —
    the ROADMAP-4 blocker was undebuggable from the artifact alone."""
    try:
        import jax
        import jax.numpy as jnp

        dev = jax.devices()[0]
        x = jnp.ones((128, 128), jnp.bfloat16)
        y = jax.jit(lambda a: a @ a)(x)
        float(jnp.float32(y[0, 0]))
    except BaseException as e:  # noqa: BLE001 — the whole point is to
        # ship the diagnosis to the parent, whatever it is
        msg = str(e).replace("\n", " ")[:500]
        print(f"PROBE_ERR {type(e).__name__}: {msg}")
        return
    print(f"PROBE_OK platform={dev.platform}")


def _force_cpu_jax() -> None:
    """Keep a CPU child off the flaky tunnel backend (the axon
    sitecustomize forces jax_platforms at import; config.update after
    import wins — same trick as tests/conftest.py)."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _run_micro() -> None:
    """Child-process body: ray_perf control-plane microbenchmarks
    (reference: python/ray/_private/ray_perf.py:95-290). Emits one
    MICRO_JSON line consumed by the parent."""
    _force_cpu_jax()
    from ray_tpu._private import ray_perf

    results = ray_perf.main(small=True)
    print("MICRO_JSON " + json.dumps(
        {r["name"]: round(r["ops_per_s"], 1) for r in results}))


def _run_allreduce() -> None:
    """Child-process body: `ray.util.collective` allreduce bandwidth —
    the second metric of record (BASELINE.json).

    Two measurements:
    - objstore backend across 2 actor processes (the gloo-equivalent
      host path): payload GB/s per rank.
    - XLA backend over 8 virtual CPU devices in one jitted psum (the
      ICI-collective shape used on real pods; CPU devices here, so the
      number validates the path, not the silicon).
    """
    _force_cpu_jax()
    import numpy as np

    out = {}

    # --- XLA backend, 8 virtual devices (env set by parent) -----------
    import jax

    if len(jax.devices()) >= 8:
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size=1, rank=0, backend="xla")
        nbytes = 32 * (1 << 20)  # 32 MiB per shard
        parts = [np.ones(nbytes // 4, np.float32) for _ in range(8)]
        col.allreduce(parts)  # compile + warm
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            r = col.allreduce(parts)
        np.asarray(r)  # sync
        dt = time.perf_counter() - t0
        out["xla_allreduce_8dev_gb_s"] = round(
            nbytes * 8 * iters / dt / 1e9, 3)
        col.destroy_collective_group()

    # --- objstore backend across 2 actors ------------------------------
    import ray_tpu
    from ray_tpu.util import collective as col_api  # noqa: F401

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank: int, world: int):
            import numpy as np

            from ray_tpu.util import collective as col

            col.init_collective_group(world, rank, backend="objstore",
                                      group_name="bench")
            self.arr = np.ones(8 * (1 << 20) // 4, np.float32)  # 8 MiB

        def step(self, iters: int) -> float:
            import time as _t

            from ray_tpu.util import collective as col

            t0 = _t.perf_counter()
            for _ in range(iters):
                col.allreduce(self.arr, group_name="bench")
            return _t.perf_counter() - t0

        def step_small(self, iters: int) -> float:
            import time as _t

            import numpy as _np

            from ray_tpu.util import collective as col

            small = _np.ones(16384, _np.float32)  # 64 KiB -> channel path
            col.allreduce(small, group_name="bench")  # channel setup
            t0 = _t.perf_counter()
            for _ in range(iters):
                col.allreduce(small, group_name="bench")
            return _t.perf_counter() - t0

    ranks = [Rank.remote(i, 2) for i in range(2)]
    ray_tpu.get([r.step.remote(2) for r in ranks])  # warm up
    # several short windows, report the best: the pipelined ring's
    # delivered bandwidth is scheduler-sensitive on oversubscribed CI
    # hosts (both ranks + daemons share ~2 cores), and peak delivered
    # bandwidth is the capability number the pipeline is accountable for
    iters = 5
    best_dt = None
    for _ in range(3):
        times = ray_tpu.get([r.step.remote(iters) for r in ranks])
        dt = max(times)
        best_dt = dt if best_dt is None else min(best_dt, dt)
    out["objstore_allreduce_2rank_gb_s"] = round(
        8 * (1 << 20) * iters / best_dt / 1e9, 3)
    # small-op latency regime: the shared-memory channel data plane
    small_iters = 300
    times = ray_tpu.get([r.step_small.remote(small_iters) for r in ranks])
    out["allreduce_64kb_2rank_ops_s"] = round(small_iters / max(times), 1)

    # --- collective v2: rank sweep + quantized-vs-exact (PR 11) --------
    out.update(_collective_v2_rows(ray_tpu))
    ray_tpu.shutdown()
    print("ALLREDUCE_JSON " + json.dumps(out))


def _collective_v2_rows(ray_tpu) -> dict:
    """GB/s-vs-ranks curve (8 MiB exact allreduce at 2/4/8 ranks on one
    host — 2 ranks ride the v1 ring, 4/8 the hierarchical arena) and the
    quantized-vs-exact tradeoff measured on the hierarchical 2x2
    fake-host topology, where the cross-host wire — the layer int8
    actually compresses — is on the path.

    Metric notes for the 1-core CI box: ``gb_s_vs_ranks`` keeps the v1
    definition (per-rank payload / wall). All N ranks timeshare ONE
    core, so total work — which grows ~linearly with N — serializes,
    and the per-rank figure necessarily falls with N; the aggregate row
    (sum of rank payloads over the same wall) is the
    hardware-normalized companion. MICROBENCH.md round 9 carries the
    full analysis."""
    import numpy as np

    from ray_tpu.util import collective as col  # noqa: F401

    @ray_tpu.remote(num_cpus=0)
    class VRank:
        def __init__(self, rank, world, gname, env=None, mib=8):
            import os

            import numpy as np

            from ray_tpu.util import collective as col

            for k, v in (env or {}).items():
                os.environ[k] = v
            self.gname = gname
            col.init_collective_group(world, rank, backend="objstore",
                                      group_name=gname)
            self.arr = np.ones(mib * (1 << 20) // 4, np.float32)

        def step(self, iters):
            import time as _t

            from ray_tpu.util import collective as col

            t0 = _t.perf_counter()
            for _ in range(iters):
                col.allreduce(self.arr, group_name=self.gname)
            return _t.perf_counter() - t0

        def reduce_once(self, arr):
            from ray_tpu.util import collective as col

            return col.allreduce(arr, group_name=self.gname)

        def destroy(self):
            from ray_tpu.util import collective as col

            col.destroy_collective_group(self.gname)
            return True

    def measure(world, gname, env=None, envs=None, iters=4, windows=2):
        ws = [VRank.remote(i, world, gname,
                           envs[i] if envs else env) for i in range(world)]
        ray_tpu.get([w.step.remote(1) for w in ws], timeout=420)  # warm
        best = None
        for _ in range(windows):
            dt = max(ray_tpu.get([w.step.remote(iters) for w in ws],
                                 timeout=420))
            best = dt if best is None else min(best, dt)
        gbs = 8 * (1 << 20) * iters / best / 1e9
        return ws, round(gbs, 3)

    def teardown(ws):
        ray_tpu.get([w.destroy.remote() for w in ws], timeout=120)
        for w in ws:
            ray_tpu.kill(w)

    rows: dict = {}
    curve = {}
    aggregate = {}
    for world in (2, 4, 8):
        ws, gbs = measure(world, f"v2sweep{world}",
                          iters=4 if world < 8 else 3)
        teardown(ws)
        curve[str(world)] = gbs
        aggregate[str(world)] = round(gbs * world, 3)
    rows["gb_s_vs_ranks"] = curve
    rows["aggregate_gb_s_vs_ranks"] = aggregate

    # quantized vs exact on the hierarchical path (2 fake hosts x 2)
    fake = [{"RAY_TPU_COLLECTIVE_TOPOLOGY_KEY": k}
            for k in ("bhA", "bhA", "bhB", "bhB")]
    ws, exact_gbs = measure(4, "v2qe_exact", envs=fake)
    teardown(ws)
    fakeq = [dict(e, **{"RAY_TPU_COLLECTIVE_QUANT": "int8"}) for e in fake]
    ws, int8_gbs = measure(4, "v2qe_int8", envs=fakeq)
    # accuracy on the SAME groups: adversarial-ish spread of magnitudes
    rng = np.random.RandomState(0)
    n = 2 * (1 << 20)
    parts = [(rng.randn(n) * 10 ** rng.randint(-2, 3)).astype(np.float32)
             for _ in range(4)]
    outs = ray_tpu.get(
        [w.reduce_once.remote(p) for w, p in zip(ws, parts)], timeout=420)
    teardown(ws)
    from ray_tpu.util.collective.v2 import quant as quant_mod

    exact = np.sum(np.stack(parts), axis=0)
    bound = quant_mod.sum_error_bound(
        parts, 512, steps=quant_mod.QUANT_STEPS_MULTI_HOST)
    err = np.abs(outs[0] - exact)

    # the transferable quantities: cross-host wire bytes per op per rank
    # (what a real NIC carries — this box's object path is zero-copy shm,
    # so wire-byte reduction shows up here, not in intra-box wall clock)
    # and standalone codec throughput
    codec = quant_mod.Int8BlockCodec(np.float32, block=512)
    seg = n // 2  # one counterpart segment (2 ranks per fake host)
    wire_exact = seg * 4
    wire_int8 = codec.wire_nbytes(seg)
    buf = np.empty(codec.wire_nbytes(n), np.uint8)
    t0 = time.perf_counter()
    for _ in range(5):
        codec.encode_into(parts[0], memoryview(buf))
    enc_gbs = n * 4 * 5 / (time.perf_counter() - t0) / 1e9
    dec_out = np.empty(n, np.float32)
    t0 = time.perf_counter()
    for _ in range(5):
        codec.decode_slice(memoryview(buf), n, 0, n, out=dec_out)
    dec_gbs = n * 4 * 5 / (time.perf_counter() - t0) / 1e9
    rows["quantized_vs_exact"] = {
        "topology": "2x2_fake_hosts",
        "exact_gb_s": exact_gbs,
        "int8_gb_s": int8_gbs,
        "int8_speedup": round(int8_gbs / max(exact_gbs, 1e-9), 3),
        "xh_wire_bytes_exact": wire_exact,
        "xh_wire_bytes_int8": wire_int8,
        "wire_reduction": round(wire_exact / wire_int8, 2),
        "codec_encode_gb_s": round(enc_gbs, 3),
        "codec_decode_gb_s": round(dec_gbs, 3),
        "max_abs_err": float(f"{err.max():.3e}"),
        "within_documented_bound": bool(np.all(err <= bound)),
    }

    # round 17: chunked overlap + simulated WAN, measured as PER-OP
    # LATENCY with think time between ops — the metric a training loop
    # feels (one allreduce per step, link idle in between). Sustained
    # back-to-back streaming is the wrong lens here: it saturates the
    # serialized per-sender link, both modes converge to wire-limited
    # throughput, and pipelining has nothing left to hide into. With
    # think time the wire cost is paid once per op and the overlapped
    # path hides per-block codec/copy/accumulate work under it. The
    # topology is one rank per fake host (the whole array is the
    # cross-host segment — the WAN-dominant regime the feature
    # targets), 32 MiB so the hideable work is real.
    def lat(world, gname, envs, mib, rounds=3):
        ws = [VRank.remote(i, world, gname, env=envs[i], mib=mib)
              for i in range(world)]
        ray_tpu.get([w.step.remote(1) for w in ws], timeout=420)  # warm
        best = None
        for _ in range(rounds):
            time.sleep(0.4)  # think time: the simulated link drains
            dt = max(ray_tpu.get([w.step.remote(1) for w in ws],
                                 timeout=420))
            best = dt if best is None else min(best, dt)
        teardown(ws)
        return best

    def wan_envs(gbps, overlap_mib=None, quant=None):
        env = {"RAY_TPU_COLLECTIVE_WAN_GBPS": str(gbps)}
        if overlap_mib is None:
            env["RAY_TPU_COLLECTIVE_OVERLAP"] = "0"
        else:
            bb = str(overlap_mib * (1 << 20))
            env.update({"RAY_TPU_COLLECTIVE_OVERLAP": "1",
                        "RAY_TPU_COLLECTIVE_OVERLAP_BLOCK_BYTES": bb,
                        "RAY_TPU_COLLECTIVE_OVERLAP_MIN_BYTES": bb})
        if quant:
            env["RAY_TPU_COLLECTIVE_QUANT"] = quant
        return [dict(env, **{"RAY_TPU_COLLECTIVE_TOPOLOGY_KEY": k})
                for k in ("wanA", "wanB")]

    # exact codec, 1 Gb/s: pipelining hides block puts + accumulate
    eb = lat(2, "v2wan_eb", wan_envs(1), 32)
    eo = lat(2, "v2wan_eo", wan_envs(1, overlap_mib=16), 32)
    rows["overlapped_vs_barriered_wan"] = {
        "topology": "2_fake_hosts_1_rank_each",
        "payload_mib": 32,
        "wan_gbps": 1,
        "overlap_block_mib": 16,
        "barriered_ms": round(eb * 1e3, 1),
        "overlapped_ms": round(eo * 1e3, 1),
        "overlap_speedup": round(eb / max(eo, 1e-9), 3),
    }
    # int8 at 0.25 Gb/s: the 4x wire cut is end-to-end wall clock now,
    # and chunked overlap additionally hides the codec itself
    xb = lat(2, "v2wan_xb", wan_envs(0.25), 32)
    qb = lat(2, "v2wan_qb", wan_envs(0.25, quant="int8"), 32)
    qo = lat(2, "v2wan_qo", wan_envs(0.25, overlap_mib=8, quant="int8"), 32)
    rows["int8_vs_exact_wan"] = {
        "topology": "2_fake_hosts_1_rank_each",
        "payload_mib": 32,
        "wan_gbps": 0.25,
        "exact_barriered_ms": round(xb * 1e3, 1),
        "int8_barriered_ms": round(qb * 1e3, 1),
        "int8_overlapped_ms": round(qo * 1e3, 1),
        "int8_e2e_speedup": round(xb / max(qb, 1e-9), 3),
        "int8_overlap_speedup": round(qb / max(qo, 1e-9), 3),
        "int8_overlapped_vs_exact": round(xb / max(qo, 1e-9), 3),
    }
    return rows


def _run_h2d() -> None:
    """Child-process body (TPU): host<->device bandwidth — the
    single-chip side of the collective story (data reaches the chip over
    PCIe before ICI ever matters).

    Measurement notes (VERDICT r4 weak #5): the source buffer is a
    fresh contiguous aligned array, every transfer is individually
    fenced with block_until_ready, and the MEDIAN per-transfer time is
    reported so one slow transfer can't halve the number. When this
    process reaches the chip through a network tunnel (axon: device_put
    serializes over the proxy) the figure measures the TUNNEL, not
    PCIe — MICROBENCH.md carries that caveat next to the number."""
    import jax
    import numpy as np

    dev = jax.devices()[0]
    nbytes = 64 * (1 << 20)
    # contiguous, page-aligned source (np.empty is malloc'd aligned for
    # large blocks); filled so no lazy-zero page faults land in the loop
    host = np.empty(nbytes // 4, np.float32)
    host.fill(1.0)
    jax.device_put(host, dev).block_until_ready()  # warm + compile path
    iters = 5
    h2d_times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        x = jax.device_put(host, dev)
        x.block_until_ready()
        h2d_times.append(time.perf_counter() - t0)
    d2h_times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = np.asarray(x)
        d2h_times.append(time.perf_counter() - t0)
    h2d = nbytes / sorted(h2d_times)[iters // 2] / 1e9
    d2h = nbytes / sorted(d2h_times)[iters // 2] / 1e9
    tunneled = bool(os.environ.get("AXON_SOCKET")
                    or "axon" in os.environ.get("JAX_PLATFORMS", ""))
    print("H2D_JSON " + json.dumps({
        "h2d_gb_s": round(h2d, 3), "d2h_gb_s": round(d2h, 3),
        "platform": dev.platform,
        "tunneled": tunneled,
    }))


def _run_bench(platform: str) -> None:
    """Child-process body: measure and print the JSON line."""
    import jax

    if platform == "cpu":
        # The axon sitecustomize forces jax_platforms="axon,cpu" at import
        # time; config.update after import wins (same trick as
        # tests/conftest.py) and keeps us off the flaky tunnel backend.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as T
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train import step as S

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~1B-param Llama shape with LoRA (frozen base in bf16 fits one
        # chip's HBM; the adapters train — the BASELINE.md target config
        # scaled to single-chip).
        cfg = T.config(
            "llama2_7b_lora",
            hidden=2048, mlp_hidden=5632, layers=16, heads=16, kv_heads=16,
            max_seq=2048, param_dtype=jnp.bfloat16,
        )
        batch, seq, iters = 8, 2048, 10
    else:
        cfg = T.config("tiny", lora_rank=8)
        batch, seq, iters = 8, 256, 5

    mesh = build_mesh(MeshSpec(), [dev])
    opt = S.default_optimizer(cfg)
    state = S.init_state(cfg, opt, mesh)
    ts = S.make_train_step(cfg, opt, mesh)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    batch_dict = {"tokens": tokens}

    # warmup (compile). float() forces a device→host transfer — the only
    # reliable sync on the axon tunnel platform (block_until_ready is a
    # no-op there).
    for _ in range(2):
        state, metrics = ts(state, batch_dict)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = ts(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss in benchmark"

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * iters / dt
    # 6*N FLOPs/token fwd+bwd — honest here: this implementation computes
    # dW for every base matmul (optax.multi_transform zeroes the *updates*
    # of frozen params, not their gradients), so forward (2N) + backward
    # dX (2N) + backward dW (2N) all execute on the MXU. Remat recompute
    # and attention S² terms are NOT counted (they'd inflate MFU).
    flops_per_tok = 6 * cfg.num_params()
    mfu = tok_s * flops_per_tok / _peak_flops(dev)

    print(json.dumps({
        "metric": "train_llama_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
    }))
    print(
        f"# device={dev.device_kind} platform={dev.platform} "
        f"model_params={cfg.num_params()/1e6:.0f}M batch={batch} seq={seq} "
        f"mfu={mfu:.3f} step_ms={dt/iters*1e3:.1f}",
        file=sys.stderr,
    )


def _try_child(platform: str, timeout: float, marker: str = '"metric"',
               extra_env: dict | None = None) -> str | None:
    """Run the measurement in a child process; return its marked line."""
    env = dict(os.environ, **{_CHILD_ENV: platform}, **(extra_env or {}))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"# bench child ({platform}) timed out", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr[-2000:])
    for line in proc.stdout.splitlines():
        line = line.strip()
        if marker in line and (line.startswith("{")
                               or line.startswith(marker)):
            return line
    print(f"# bench child ({platform}) rc={proc.returncode}, no JSON",
          file=sys.stderr)
    return None


def _secondary_metrics(tpu_ok: bool) -> dict:
    """Microbench + allreduce + h2d children; never fatal."""
    detail: dict = {}
    line = _try_child("micro", 420.0, marker="MICRO_JSON")
    if line:
        detail["microbench_ops_per_s"] = json.loads(
            line[len("MICRO_JSON "):])
    line = _try_child(
        "allreduce", 420.0, marker="ALLREDUCE_JSON",
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    if line:
        detail["collective_allreduce_gb_s"] = json.loads(
            line[len("ALLREDUCE_JSON "):])
    if tpu_ok:
        line = _try_child("h2d", 300.0, marker="H2D_JSON")
        if line:
            detail["chip_transfer_gb_s"] = json.loads(
                line[len("H2D_JSON "):])
    return detail


def _run_micro_smoke() -> None:
    """CPU-only data-plane smoke (<60s): puts/gets/channel/allreduce plus
    the payload-copy counters, so a copy regression on the zero-copy put
    path fails loudly in tier-1 instead of silently halving bandwidth."""
    _force_cpu_jax()
    import numpy as np

    import ray_tpu
    from ray_tpu._private import serialization as ser
    from ray_tpu.experimental import TensorChannel

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    out: dict = {}
    arr = np.zeros((512, 512), np.float32)  # 1 MiB

    def rate(fn, n):
        for _ in range(3):
            fn()
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return round(n / (time.perf_counter() - t0), 1)

    before = ser.copy_stats()
    out["put_1mb_ops_s"] = rate(lambda: ray_tpu.put(arr), 50)
    after = ser.copy_stats()
    out["put_payload_copies"] = (
        after["copies"]["put"] - before["copies"]["put"])
    ref = ray_tpu.put(arr)
    out["get_1mb_ops_s"] = rate(lambda: ray_tpu.get(ref), 50)
    after2 = ser.copy_stats()
    out["get_payload_copies_per_op"] = round(
        (after2["copies"]["get"] - after["copies"]["get"]) / 53.0, 2)

    tch = TensorChannel((512, 512), "float32")
    trd = tch.reader()

    def chan_op():
        tch.write(arr)
        trd.read_view()
        trd.release()

    out["tensor_channel_1mb_ops_s"] = rate(chan_op, 100)
    tch.close()

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank, world):
            from ray_tpu.util import collective as col

            col.init_collective_group(
                world, rank, backend="objstore", group_name="smoke")
            self.arr = np.ones(4 * (1 << 20) // 4, np.float32)

        def step(self, iters):
            import time as _t

            from ray_tpu.util import collective as col

            t0 = _t.perf_counter()
            for _ in range(iters):
                col.allreduce(self.arr, group_name="smoke")
            return _t.perf_counter() - t0

    ranks = [Rank.remote(i, 2) for i in range(2)]
    ray_tpu.get([r.step.remote(1) for r in ranks])
    times = ray_tpu.get([r.step.remote(5) for r in ranks])
    out["allreduce_4mb_2rank_gb_s"] = round(
        4 * (1 << 20) * 5 / max(times) / 1e9, 3)
    ray_tpu.shutdown()
    print("MICRO_SMOKE_JSON " + json.dumps(out))


def _run_obs_micro() -> None:
    """Flight-recorder overhead micro (PR 20): the cost of lifecycle
    marks with the recorder OFF (the default every hot path pays), ON
    (one ring append), plus the task-sampling decision and a full-ring
    ``dump_now``. The disabled number is the one the overhead-guard
    test budgets — instrumentation nobody asked for must be ~free."""
    import tempfile

    from ray_tpu.observability import dump as obs_dump
    from ray_tpu.observability import events as obs_events
    from ray_tpu.observability import timeline

    out: dict = {}
    n_off = 1_000_000
    timeline.configure(enabled=False)
    t0 = time.perf_counter()
    for _ in range(n_off):
        timeline.mark_actor("bench_actor", "submit")
    out["mark_disabled_ns"] = round(
        (time.perf_counter() - t0) / n_off * 1e9, 1)

    timeline.configure(enabled=True, task_sample=1.0)
    n_on = 100_000
    t0 = time.perf_counter()
    for _ in range(n_on):
        timeline.mark_actor("bench_actor", "submit")
    out["mark_enabled_us"] = round(
        (time.perf_counter() - t0) / n_on * 1e6, 2)
    t0 = time.perf_counter()
    for _ in range(n_on):
        timeline.task_sampled("aabbccdd" * 4)
    out["task_sampled_ns"] = round(
        (time.perf_counter() - t0) / n_on * 1e9, 1)
    out["overhead_ratio"] = round(
        out["mark_enabled_us"] * 1e3 / max(out["mark_disabled_ns"], 0.1),
        1)

    # dump latency with the ring at capacity (the failure-path cost)
    with tempfile.TemporaryDirectory() as d:
        os.environ["RAY_TPU_DEBUG_DIR"] = d
        try:
            t0 = time.perf_counter()
            path = obs_dump.dump_now("bench", force=True)
            out["dump_full_ring_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
            out["dump_shard_kb"] = round(
                os.path.getsize(path) / 1024.0, 1) if path else None
        finally:
            os.environ.pop("RAY_TPU_DEBUG_DIR", None)
    out["ring_events"] = len(obs_events.local_events())
    timeline.configure(enabled=False)
    print("OBS_MICRO_JSON " + json.dumps(out))


def _run_serve_micro() -> None:
    """Serve front-door dispatch micro (PR 12): unary RTT and streaming
    chunk throughput through the HTTP proxy, measured end to end over
    real sockets. Merged into MICROBENCH.json as ``serve_proxy`` (the
    round-10 before/after row: the pre-PR proxy burned 2-3 executor-
    thread hops per request and one PER CHUNK on streams; dispatch now
    rides the proxy's event loop straight into the fastpath-coded RPC
    plane)."""
    import http.client
    import statistics
    import threading

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)

    @serve.deployment(name="echo", max_ongoing_requests=64)
    def echo(x):
        return x

    @serve.deployment(name="chunks", max_ongoing_requests=64)
    def chunks(n):
        for i in range(int(n)):
            yield {"i": i}

    @serve.deployment(name="tokens", max_ongoing_requests=64)
    def tokens(n):
        # LLM-shaped stream: one yield per decode step (~30 ms/token)
        for i in range(int(n)):
            time.sleep(0.03)
            yield {"t": i}

    @serve.deployment(name="prefill", max_ongoing_requests=64)
    def prefill(n):
        # cold-start stream shape: a prefill-length pause, then tokens.
        # NOTHING is buffered before the first yield, so the consumer's
        # wait for the first byte really blocks — the shape that
        # serializes on a thread-pool proxy (5 default-executor threads
        # on a 1-CPU box) and that loop-native dispatch rides for free.
        time.sleep(0.25)
        for i in range(int(n)):
            yield {"t": i}

    serve.run(echo.bind())
    serve.run(chunks.bind(), name="chunks")
    serve.run(tokens.bind(), name="tokens")
    serve.run(prefill.bind(), name="prefill")
    port = serve.start_http_proxy(port=0)

    def post(conn, path, payload):
        conn.request("POST", path, body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        return conn.getresponse()

    out = {}
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        for _ in range(20):  # warm: replica start + handle resolution
            post(conn, "/echo", {"w": 1}).read()
        # -- unary sequential RTT --------------------------------------
        lats = []
        for i in range(300):
            t0 = time.perf_counter()
            post(conn, "/echo", {"i": i}).read()
            lats.append((time.perf_counter() - t0) * 1000)
        lats.sort()
        out["unary_rtt_p50_ms"] = round(statistics.median(lats), 2)
        out["unary_rtt_p99_ms"] = round(lats[int(len(lats) * 0.99) - 1], 2)
        # -- unary concurrent throughput -------------------------------
        n_threads, per = 32, 20
        done = []

        def worker():
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            for i in range(per):
                post(c, "/echo", {"i": i}).read()
            c.close()
            done.append(1)

        t0 = time.perf_counter()
        ts = [threading.Thread(target=worker, daemon=True)
              for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        wall = time.perf_counter() - t0
        assert len(done) == n_threads
        out["unary_concurrent_rps"] = round(n_threads * per / wall, 1)
        # -- streaming chunk throughput (8 concurrent streams) ---------
        n_streams, n_chunks = 8, 50
        stream_walls = []

        def stream_worker():
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            t0 = time.perf_counter()
            resp = post(c, "/chunks", n_chunks)
            got = 0
            while True:
                line = resp.readline()
                if not line:
                    break
                if line.strip():
                    got += 1
            assert got == n_chunks, got
            stream_walls.append(time.perf_counter() - t0)
            c.close()

        ts = [threading.Thread(target=stream_worker, daemon=True)
              for _ in range(n_streams)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        wall = time.perf_counter() - t0
        assert len(stream_walls) == n_streams
        out["stream_chunks_per_s"] = round(n_streams * n_chunks / wall, 1)
        out["stream_wall_p50_ms"] = round(
            statistics.median(stream_walls) * 1000, 1)
        # -- 32 concurrent SLOW (LLM-shaped) streams -------------------
        # 20 tokens x 30 ms = 600 ms nominal per stream. Each in-flight
        # token wait held an executor thread in the pre-PR proxy — with
        # the default pool (~cpu+4 threads) 32 streams serialize; loop-
        # native dispatch keeps every stream at its nominal latency.
        n_slow, n_tok = 32, 20
        slow_walls = []

        def slow_worker():
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            t0 = time.perf_counter()
            resp = post(c, "/tokens", n_tok)
            got = 0
            while True:
                line = resp.readline()
                if not line:
                    break
                if line.strip():
                    got += 1
            assert got == n_tok, got
            slow_walls.append(time.perf_counter() - t0)
            c.close()

        ts = [threading.Thread(target=slow_worker, daemon=True)
              for _ in range(n_slow)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=240)
        assert len(slow_walls) == n_slow
        slow_walls.sort()
        out["slow_stream_nominal_ms"] = n_tok * 30
        out["slow_stream_32x_wall_p50_ms"] = round(
            statistics.median(slow_walls) * 1000, 1)
        out["slow_stream_32x_wall_p99_ms"] = round(
            slow_walls[int(n_slow * 0.99) - 1] * 1000, 1)
        # -- 48 concurrent cold-start streams: time to first byte ------
        # 250 ms nominal prefill before the first token; the first-byte
        # wait cannot be hidden by producer-side buffering
        n_cold = 48
        ttfb = []

        def cold_worker():
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            t0 = time.perf_counter()
            resp = post(c, "/prefill", 3)
            line = resp.readline()
            assert line
            ttfb.append(time.perf_counter() - t0)
            resp.read()
            c.close()

        ts = [threading.Thread(target=cold_worker, daemon=True)
              for _ in range(n_cold)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=240)
        assert len(ttfb) == n_cold
        ttfb.sort()
        out["cold_stream_nominal_first_byte_ms"] = 250
        out["cold_stream_48x_first_byte_p50_ms"] = round(
            statistics.median(ttfb) * 1000, 1)
        out["cold_stream_48x_first_byte_p99_ms"] = round(
            ttfb[int(n_cold * 0.99) - 1] * 1000, 1)
        conn.close()
    finally:
        serve.stop_http_proxy()
        serve.shutdown()
        ray_tpu.shutdown()

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MICROBENCH.json")
    try:
        with open(path) as f:
            detail = json.load(f)
    except (OSError, ValueError):
        detail = {}
    detail["serve_proxy"] = out
    with open(path, "w") as f:
        json.dump(detail, f, indent=1)
    print("# serve_proxy " + json.dumps(out))


def _probe_tpu(max_attempts: int):
    """Short child-process probe. Returns ``(ok, error)``: ``ok`` is
    True only on an affirmative TPU verdict; ``error`` carries the
    captured exception class + message (or timeout/crash diagnosis)
    from the LAST failed attempt so the artifact records WHY the probe
    failed, not just that it did. A completed CPU-only probe is
    authoritative (no retry)."""
    env = dict(os.environ, **{_CHILD_ENV: "probe"})
    error = None
    for attempt in range(max_attempts):
        clean_verdict = False
        ok = False
        try:
            probe = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=240,
            )
            clean_verdict = "PROBE_OK" in probe.stdout
            ok = clean_verdict and "platform=tpu" in probe.stdout
            for line in probe.stdout.splitlines():
                if line.startswith("PROBE_ERR "):
                    error = line[len("PROBE_ERR "):].strip()
                    break
            else:
                if clean_verdict and not ok:
                    error = "no TPU device (probe completed on " + (
                        probe.stdout.split("platform=", 1)[1].split()[0]
                        if "platform=" in probe.stdout else "?") + ")"
                elif not clean_verdict:
                    # child crashed without reaching the guard (OOM
                    # kill, segfault in a backend lib): last stderr
                    # line is the best diagnosis available. Overwrite
                    # unconditionally — the recorded error always
                    # describes the LAST failed attempt, matching the
                    # PROBE_ERR and timeout branches.
                    tail = [ln for ln in probe.stderr.splitlines()
                            if ln.strip()]
                    error = ("child exited rc=%d: %s" % (
                        probe.returncode,
                        tail[-1][:300] if tail else "no stderr"))
        except subprocess.TimeoutExpired:
            ok = False
            error = "TimeoutExpired: TPU probe exceeded 240s " \
                    "(hung backend/tunnel)"
        if ok:
            return True, None
        if clean_verdict:
            return False, error  # a verdict, not a flake — no retry
        print(f"# TPU probe attempt {attempt + 1} failed/hung",
              file=sys.stderr)
    return False, error


_LAST_TPU_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_TPU.json")


def _record_last_tpu(line: str) -> None:
    """A fresh TPU headline: persist as the last-known-good number."""
    try:
        with open(_LAST_TPU_PATH, "w") as f:
            json.dump({
                "headline": json.loads(line),
                "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                "stale": False,
            }, f, indent=1)
    except (OSError, ValueError) as e:
        print(f"# could not record BENCH_LAST_TPU.json: {e}",
              file=sys.stderr)


def _carry_stale_tpu() -> None:
    """No TPU this window: re-mark the recorded last-known-good number
    stale and echo it into the tail, so a CPU-only round still carries
    the most recent real TPU figure (clearly labeled, never mistaken
    for a fresh measurement)."""
    try:
        with open(_LAST_TPU_PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return  # no TPU number has ever been recorded
    data["stale"] = True
    try:
        with open(_LAST_TPU_PATH, "w") as f:
            json.dump(data, f, indent=1)
    except OSError:
        pass
    print(f"# last_known_tpu {json.dumps(data)}")


def main() -> None:
    if "--micro-smoke" in sys.argv:
        _run_micro_smoke()
        return
    if "--serve-micro" in sys.argv:
        _run_serve_micro()
        return
    if "--obs-micro" in sys.argv:
        _run_obs_micro()
        return
    child_platform = os.environ.get(_CHILD_ENV)
    if child_platform == "probe":
        _run_probe()
        return
    if child_platform == "micro":
        _run_micro()
        return
    if child_platform == "allreduce":
        _run_allreduce()
        return
    if child_platform == "h2d":
        _run_h2d()
        return
    if child_platform:
        _run_bench(child_platform)
        return

    # Parent: short TPU probe decides whether the tunnel backend is usable
    # (round-1 failure mode: it HANGS rather than erroring, so committing
    # to a full-length TPU attempt first risks never printing a number).
    # Bounded init + ONE retry (VERDICT round-6): a transiently-flaky
    # tunnel gets a second chance before the run is stamped CPU-only —
    # and a LAST re-probe runs at the END of the window (after the CPU
    # measurements) before the run settles for a CPU headline.
    tpu_ok, tpu_err = _probe_tpu(max_attempts=2)
    if not tpu_ok:
        print(f"# TPU probe found no usable TPU — falling back to CPU; "
              f"results are stamped tpu_probe=failed "
              f"({tpu_err or 'no diagnosis captured'})", file=sys.stderr)

    # secondary metrics of record: control-plane ops/s + allreduce GB/s
    # (full detail lands in MICROBENCH.json; compact copies in the tail)
    detail = _secondary_metrics(tpu_ok)
    # a CPU number must never be mistaken for a TPU regression: the
    # probe verdict rides in the artifact itself — WITH the captured
    # exception class+message, so a failed probe is debuggable from
    # MICROBENCH.json alone (ROADMAP item 4 blocker)
    detail["tpu_probe"] = "ok" if tpu_ok else "failed"
    if not tpu_ok and tpu_err:
        detail["tpu_probe_error"] = tpu_err
    for key, val in detail.items():
        print(f"# {key} {json.dumps(val)}")
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "MICROBENCH.json"), "w") as f:
            json.dump(detail, f, indent=1)
    except OSError as e:
        print(f"# could not write MICROBENCH.json: {e}", file=sys.stderr)
    # scalability-envelope results (produced by scale_bench.py, which is
    # too long to rerun inside the bench window): echo into the tail so
    # every round's BENCH artifact records them
    try:
        sb_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "SCALEBENCH.json")
        with open(sb_path) as f:
            sb = json.load(f)
        for key in ("many_tasks", "many_actors", "many_pgs", "collective"):
            if key in sb:
                print(f"# scalebench.{key} {json.dumps(sb[key])}")
    except (OSError, ValueError):
        pass

    if tpu_ok:
        line = _try_child("tpu", 1200.0)
        if line is not None:
            _record_last_tpu(line)
            print(line)
            return
    cpu_line = _try_child("cpu", 900.0)
    if not tpu_ok:
        # End-of-window re-probe: a tunnel that was down when the window
        # opened may be back; one more chance at a REAL TPU number
        # before settling for CPU (VERDICT item 1, beyond the round-6
        # single retry).
        print("# end-of-window TPU re-probe before settling for CPU",
              file=sys.stderr)
        if _probe_tpu(max_attempts=1)[0]:
            line = _try_child("tpu", 1200.0)
            if line is not None:
                _record_last_tpu(line)
                print("# late TPU probe succeeded; headline is TPU",
                      file=sys.stderr)
                print(line)
                return
        # still CPU-only: carry the stale-marked last-known-good TPU
        # figure into the tail
        _carry_stale_tpu()
    if cpu_line is not None:
        print(cpu_line)
        return

    try:
        _run_bench("cpu")
    except Exception as exc:  # noqa: BLE001 — a number must always appear
        print(f"# inline CPU fallback failed: {exc!r}", file=sys.stderr)
        print(json.dumps({
            "metric": "train_llama_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
        }))


if __name__ == "__main__":
    main()
