"""Benchmark of record: flagship Llama-family LoRA train step, tokens/sec/chip.

Matches BASELINE.json's metric ("Ray Train Llama tokens/sec/chip");
``vs_baseline`` is MFU / 0.35 — the reference's north-star target is
>=35% MFU on the Llama LoRA fine-tune (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness contract (VERDICT round 1, item 1): the TPU tunnel backend can be
transiently unavailable, and a bare ``jax.devices()`` crash means no perf
number at all. So the parent process runs the measurement in a CHILD process:
try the TPU backend (with retries), then fall back to a CPU run — whichever
child first emits a benchmark JSON line wins and the parent re-prints it.
A JSON line is ALWAYS produced.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


# Peak bf16 FLOP/s per chip (public spec sheets).
PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5e": 197e12, "v5p": 459e12, "v6e": 918e12, "v6p": 4614e12 / 2,
}

_CHILD_ENV = "RAY_TPU_BENCH_CHILD"


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind.replace(" ", "").replace("tpu", ""):
            return val
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU — MFU not meaningful, still report


def _run_probe() -> None:
    """Child-process body: quick TPU viability check — backend init plus a
    tiny compiled matmul. Bounds time-to-first-number: a hanging tunnel
    backend costs one short probe timeout, not a full benchmark timeout."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    float(jnp.float32(y[0, 0]))
    print(f"PROBE_OK platform={dev.platform}")


def _run_bench(platform: str) -> None:
    """Child-process body: measure and print the JSON line."""
    import jax

    if platform == "cpu":
        # The axon sitecustomize forces jax_platforms="axon,cpu" at import
        # time; config.update after import wins (same trick as
        # tests/conftest.py) and keeps us off the flaky tunnel backend.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as T
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train import step as S

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~1B-param Llama shape with LoRA (frozen base in bf16 fits one
        # chip's HBM; the adapters train — the BASELINE.md target config
        # scaled to single-chip).
        cfg = T.config(
            "llama2_7b_lora",
            hidden=2048, mlp_hidden=5632, layers=16, heads=16, kv_heads=16,
            max_seq=2048, param_dtype=jnp.bfloat16,
        )
        batch, seq, iters = 8, 2048, 10
    else:
        cfg = T.config("tiny", lora_rank=8)
        batch, seq, iters = 8, 256, 5

    mesh = build_mesh(MeshSpec(), [dev])
    opt = S.default_optimizer(cfg)
    state = S.init_state(cfg, opt, mesh)
    ts = S.make_train_step(cfg, opt, mesh)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    batch_dict = {"tokens": tokens}

    # warmup (compile). float() forces a device→host transfer — the only
    # reliable sync on the axon tunnel platform (block_until_ready is a
    # no-op there).
    for _ in range(2):
        state, metrics = ts(state, batch_dict)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = ts(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss in benchmark"

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * iters / dt
    # 6*N FLOPs/token fwd+bwd — honest here: this implementation computes
    # dW for every base matmul (optax.multi_transform zeroes the *updates*
    # of frozen params, not their gradients), so forward (2N) + backward
    # dX (2N) + backward dW (2N) all execute on the MXU. Remat recompute
    # and attention S² terms are NOT counted (they'd inflate MFU).
    flops_per_tok = 6 * cfg.num_params()
    mfu = tok_s * flops_per_tok / _peak_flops(dev)

    print(json.dumps({
        "metric": "train_llama_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
    }))
    print(
        f"# device={dev.device_kind} platform={dev.platform} "
        f"model_params={cfg.num_params()/1e6:.0f}M batch={batch} seq={seq} "
        f"mfu={mfu:.3f} step_ms={dt/iters*1e3:.1f}",
        file=sys.stderr,
    )


def _try_child(platform: str, timeout: float) -> str | None:
    """Run the measurement in a child process; return its JSON line or None."""
    env = dict(os.environ, **{_CHILD_ENV: platform})
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"# bench child ({platform}) timed out", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr[-2000:])
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            return line
    print(f"# bench child ({platform}) rc={proc.returncode}, no JSON",
          file=sys.stderr)
    return None


def main() -> None:
    child_platform = os.environ.get(_CHILD_ENV)
    if child_platform == "probe":
        _run_probe()
        return
    if child_platform:
        _run_bench(child_platform)
        return

    # Parent: short TPU probe decides whether the tunnel backend is usable
    # (round-1 failure mode: it HANGS rather than erroring, so committing
    # to a full-length TPU attempt first risks never printing a number).
    attempts = []
    env = dict(os.environ, **{_CHILD_ENV: "probe"})
    try:
        probe = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=240,
        )
        tpu_ok = "PROBE_OK" in probe.stdout and "platform=tpu" in probe.stdout
    except subprocess.TimeoutExpired:
        tpu_ok = False
    if tpu_ok:
        attempts = [("tpu", 1200.0), ("cpu", 900.0)]
    else:
        print("# TPU probe failed/hung — falling back to CPU", file=sys.stderr)
        attempts = [("cpu", 900.0)]
    for platform, timeout in attempts:
        line = _try_child(platform, timeout)
        if line is not None:
            print(line)
            return

    try:
        _run_bench("cpu")
    except Exception as exc:  # noqa: BLE001 — a number must always appear
        print(f"# inline CPU fallback failed: {exc!r}", file=sys.stderr)
        print(json.dumps({
            "metric": "train_llama_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
        }))


if __name__ == "__main__":
    main()
