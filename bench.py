"""Benchmark of record: flagship Llama-family LoRA train step, tokens/sec/chip.

Matches BASELINE.json's metric ("Ray Train Llama tokens/sec/chip");
``vs_baseline`` is MFU / 0.35 — the reference's north-star target is
>=35% MFU on the Llama LoRA fine-tune (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever jax.devices() offers (1 real TPU chip under the
driver; CPU fallback shrinks the model so CI still produces a number).
"""

from __future__ import annotations

import json
import sys
import time


# Peak bf16 FLOP/s per chip (public spec sheets).
PEAK_FLOPS = {
    "v2": 45e12, "v3": 123e12, "v4": 275e12,
    "v5e": 197e12, "v5p": 459e12, "v6e": 918e12, "v6p": 4614e12 / 2,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind.replace(" ", "").replace("tpu", ""):
            return val
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU — MFU not meaningful, still report


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import transformer as T
    from ray_tpu.parallel.mesh import MeshSpec, build_mesh
    from ray_tpu.train import step as S

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~1B-param Llama shape with LoRA (frozen base in bf16 fits one
        # chip's HBM; the adapters train — the BASELINE.md target config
        # scaled to single-chip).
        cfg = T.config(
            "llama2_7b_lora",
            hidden=2048, mlp_hidden=5632, layers=16, heads=16, kv_heads=16,
            max_seq=2048, param_dtype=jnp.bfloat16,
        )
        batch, seq, iters = 8, 2048, 10
    else:
        cfg = T.config("tiny", lora_rank=8)
        batch, seq, iters = 8, 256, 5

    mesh = build_mesh(MeshSpec(), [dev])
    opt = S.default_optimizer(cfg)
    state = S.init_state(cfg, opt, mesh)
    ts = S.make_train_step(cfg, opt, mesh)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    batch_dict = {"tokens": tokens}

    # warmup (compile). float() forces a device→host transfer — the only
    # reliable sync on the axon tunnel platform (block_until_ready is a
    # no-op there).
    for _ in range(2):
        state, metrics = ts(state, batch_dict)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = ts(state, batch_dict)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss in benchmark"

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * iters / dt
    # 6*N FLOPs/token fwd+bwd on the dense path (LoRA trains adapters but
    # backward still traverses the base matmuls; 6N is the standard
    # accounting and matches the reference's MFU definition).
    flops_per_tok = 6 * cfg.num_params()
    mfu = tok_s * flops_per_tok / _peak_flops(dev)

    print(json.dumps({
        "metric": "train_llama_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
    }))
    print(
        f"# device={dev.device_kind} platform={dev.platform} "
        f"model_params={cfg.num_params()/1e6:.0f}M batch={batch} seq={seq} "
        f"mfu={mfu:.3f} step_ms={dt/iters*1e3:.1f}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
