"""ray_tpu — a TPU-native distributed computing framework.

A brand-new framework with the capabilities of Ray (tasks, actors, objects,
placement groups, Train/Tune/Serve/Data/RL libraries), designed TPU-first:
the resource model speaks TPU chips and pod slices natively, worker groups
bootstrap ``jax.distributed`` + MEGASCALE instead of NCCL rendezvous, and all
hot-path parallelism is expressed as GSPMD/``shard_map`` shardings over ICI.

Public API mirrors the reference (python/ray/__init__.py) where it makes
sense: ``init, shutdown, remote, get, put, wait, kill, cancel, get_actor``.
"""

from __future__ import annotations

import inspect
from typing import Any

from ray_tpu._version import version as __version__
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    UniqueID,
    WorkerID,
)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.streaming import ObjectRefGenerator
from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)
from ray_tpu._private.profiling import (
    start_tpu_profile,
    stop_tpu_profile,
    timeline,
    tpu_profile,
)
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu import exceptions  # noqa: F401
from ray_tpu import observability  # noqa: F401 — event bus + tracing

_ALLOWED_TASK_OPTIONS = {
    "num_returns",
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "memory",
    "resources",
    "max_retries",
    "retry_exceptions",
    "scheduling_strategy",
    "runtime_env",
    "name",
    "max_calls",
}
_ALLOWED_ACTOR_OPTIONS = {
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "memory",
    "resources",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "max_pending_calls",
    "name",
    "namespace",
    "lifetime",
    "get_if_exists",
    "scheduling_strategy",
    "runtime_env",
}


def remote(*args, **kwargs):
    """``@ray_tpu.remote`` — turn a function into a RemoteFunction or a class
    into an ActorClass (reference: python/ray/_private/worker.py:3391)."""

    def _make(target):
        if inspect.isclass(target):
            bad = set(kwargs) - _ALLOWED_ACTOR_OPTIONS
            if bad:
                raise ValueError(f"Invalid actor options: {sorted(bad)}")
            return ActorClass(target, kwargs)
        if callable(target):
            bad = set(kwargs) - _ALLOWED_TASK_OPTIONS
            if bad:
                raise ValueError(f"Invalid task options: {sorted(bad)}")
            return RemoteFunction(target, kwargs)
        raise TypeError("@ray_tpu.remote requires a function or class")

    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        return _make(args[0])
    if args:
        raise TypeError("@ray_tpu.remote accepts only keyword options")
    return _make


__all__ = [
    "__version__",
    "init",
    "observability",
    "timeline",
    "tpu_profile",
    "start_tpu_profile",
    "stop_tpu_profile",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "method",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "exceptions",
    "ActorID",
    "JobID",
    "NodeID",
    "ObjectID",
    "PlacementGroupID",
    "TaskID",
    "UniqueID",
    "WorkerID",
]
