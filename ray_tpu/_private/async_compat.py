"""Bridging ObjectRefs into asyncio (reference: python/ray/_private/async_compat.py)."""

from __future__ import annotations

import asyncio
import inspect

ASYNC_ACTOR_DEFAULT_CONCURRENCY = 100


def has_async_methods(obj) -> bool:
    """True if a class/instance defines any ``async def`` method — the
    actor then runs an event loop and EVERY method executes on it (sync
    ones included, serialized), matching the reference's async actors.
    Shared by the cluster worker and local mode so they can't drift."""
    for m in dir(obj):
        if m.startswith("__"):
            continue
        fn = getattr(obj, m, None)
        if inspect.iscoroutinefunction(fn):
            return True
        if inspect.isasyncgenfunction(fn):
            raise TypeError(
                f"async generator method {m!r} is not supported yet; use a "
                "sync generator (streams) or an async method returning a list"
            )
    return False


def as_asyncio_future(ref) -> "asyncio.Future":
    loop = asyncio.get_event_loop()
    aio_fut: asyncio.Future = loop.create_future()

    from ray_tpu._private import worker as _worker_mod

    cf = _worker_mod.global_worker.core.as_future(ref)

    def _done(f):
        if aio_fut.cancelled():
            return
        exc = f.exception()
        if exc is not None:
            loop.call_soon_threadsafe(aio_fut.set_exception, exc)
        else:
            loop.call_soon_threadsafe(aio_fut.set_result, f.result())

    cf.add_done_callback(_done)
    return aio_fut
