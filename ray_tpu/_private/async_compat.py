"""Bridging ObjectRefs into asyncio (reference: python/ray/_private/async_compat.py)."""

from __future__ import annotations

import asyncio


def as_asyncio_future(ref) -> "asyncio.Future":
    loop = asyncio.get_event_loop()
    aio_fut: asyncio.Future = loop.create_future()

    from ray_tpu._private import worker as _worker_mod

    cf = _worker_mod.global_worker.core.as_future(ref)

    def _done(f):
        if aio_fut.cancelled():
            return
        exc = f.exception()
        if exc is not None:
            loop.call_soon_threadsafe(aio_fut.set_exception, exc)
        else:
            loop.call_soon_threadsafe(aio_fut.set_result, f.result())

    cf.add_done_callback(_done)
    return aio_fut
