"""Chaos utilities — process-level fault injection for tests.

Reference: python/ray/_private/test_utils.py:1355 (`ResourceKillerActor`
/ `NodeKillerBase` used by python/ray/tests/chaos/ and the nightly
chaos suite). RPC-level injection lives in _private/rpc.py
(``testing_rpc_failure``, mirroring src/ray/rpc/rpc_chaos.h — including
the ``Method=prob:delay_ms`` latency form; ``rpc_delay_spec`` below
builds one). ``PreemptionInjector`` models TPU capacity loss: a short
drain notice with a jittered deadline, then the host vanishes.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

import psutil


# shared default rng for helpers called without an explicit one: seeded
# (replayable across runs) while still varying across successive calls
_DEFAULT_RNG = random.Random(0)


def rpc_delay_spec(method: str, prob: float, delay_ms: float) -> str:
    """One ``testing_rpc_failure`` entry injecting latency instead of a
    failure (join multiple with commas)."""
    return f"{method}={prob:g}:{delay_ms:g}"


def list_worker_pids(raylet_pid: int) -> List[int]:
    """PIDs of worker processes owned by a raylet. Covers both spawn
    paths: cold-started workers (``default_worker`` in the cmdline) and
    zygote-forked workers (which inherit the zygote's cmdline — their
    kernel comm is stamped ``rtw:<id>``, and the zygote parent itself
    must NOT be a kill candidate)."""
    out = []
    try:
        parent = psutil.Process(raylet_pid)
        for child in parent.children(recursive=True):
            try:
                cmd = " ".join(child.cmdline())
                comm = child.name()
            except psutil.Error:
                continue
            if "default_worker" in cmd or comm.startswith("rtw:"):
                out.append(child.pid)
    except psutil.Error:
        pass
    return out


def kill_random_worker(cluster, rng: Optional[random.Random] = None) -> Optional[int]:
    """SIGKILL one random worker process somewhere in the cluster;
    returns its pid (None if no workers are running)."""
    # default is SEEDED but shared: replayable across runs, yet
    # successive no-rng calls still draw a fresh value each time (a
    # per-call Random(0) would kill the same list position forever)
    rng = rng or _DEFAULT_RNG
    pids: List[int] = []
    for node in cluster.nodes:
        pids.extend(list_worker_pids(node.proc.pid))
    if not pids:
        return None
    victim = rng.choice(pids)
    try:
        psutil.Process(victim).kill()
        return victim
    except psutil.Error:
        return None


class WorkerKiller:
    """Background thread killing a random worker every ``interval_s``
    (reference: ResourceKillerActor, test_utils.py:1355)."""

    def __init__(self, cluster, interval_s: float = 1.0,
                 max_kills: int = 1_000_000, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.kills = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.interval_s) and \
                    self.kills < self.max_kills:
                if kill_random_worker(self.cluster, self._rng) is not None:
                    self.kills += 1

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="chaos-worker-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class NodeKiller:
    """Kills random NON-HEAD nodes of a cluster_utils.Cluster at an
    interval (reference: NodeKillerBase, test_utils.py:1451)."""

    def __init__(self, cluster, interval_s: float = 5.0, max_kills: int = 1,
                 seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def kill_one(self) -> Optional[str]:
        candidates = [n for n in self.cluster.nodes if not n.is_head]
        if not candidates:
            return None
        victim = self._rng.choice(candidates)
        self.cluster.remove_node(victim)
        self.killed.append(victim.node_id)
        return victim.node_id

    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.interval_s) and \
                    len(self.killed) < self.max_kills:
                self.kill_one()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="chaos-node-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class PreemptionInjector:
    """TPU-style preemption notices against a cluster_utils.Cluster:
    a random NON-HEAD node gets a graceful ``DrainNode`` with reason
    PREEMPTION and a seeded, jittered deadline; at deadline + grace the
    host is hard-killed if it hasn't deregistered itself (real
    preemptions don't wait for a polite exit). Seeded for reproducible
    chaos runs."""

    def __init__(self, cluster, interval_s: float = 10.0,
                 max_preemptions: int = 1, seed: int = 0,
                 deadline_s: float = 10.0, jitter_s: float = 2.0,
                 kill_grace_s: float = 3.0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_preemptions = max_preemptions
        self.deadline_s = deadline_s
        self.jitter_s = jitter_s
        self.kill_grace_s = kill_grace_s
        self.preempted: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def preempt_one(self) -> Optional[str]:
        """Issue one preemption notice; blocks until the node is gone
        (deadline + grace at most). Returns the node id, or None when
        only the head node remains."""
        from ray_tpu._private.drain import REASON_PREEMPTION
        from ray_tpu._private.node import kill_process_tree
        from ray_tpu._private.rpc import RpcClient

        candidates = [n for n in self.cluster.nodes if not n.is_head]
        if not candidates:
            return None
        victim = self._rng.choice(candidates)
        deadline = max(0.5, self.deadline_s + self._rng.uniform(
            -self.jitter_s, self.jitter_s))
        client = RpcClient("127.0.0.1", self.cluster.gcs_port)
        try:
            client.call("DrainNode", node_id=victim.node_id,
                        reason=REASON_PREEMPTION, deadline_s=deadline,
                        timeout=10)
        except Exception:  # noqa: BLE001 — the hard kill below still fires
            pass
        finally:
            client.close()
        # the raylet normally deregisters and exits on its own; the
        # preemption hard-stop at deadline + grace is the contract
        stop_at = time.monotonic() + deadline + self.kill_grace_s
        while time.monotonic() < stop_at and not self._stop.is_set():
            if victim.proc.poll() is not None:
                break
            time.sleep(0.1)
        kill_process_tree(victim.proc, force=True)
        if victim in self.cluster.nodes:
            self.cluster.nodes.remove(victim)
        self.preempted.append(victim.node_id)
        return victim.node_id

    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.interval_s) and \
                    len(self.preempted) < self.max_preemptions:
                self.preempt_one()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="chaos-preemption")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
