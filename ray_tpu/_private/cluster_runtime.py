"""ClusterRuntime — the real multi-process execution backend.

Reference analogue: the Cython CoreWorker (python/ray/_raylet.pyx:2851) over
src/ray/core_worker/, talking to a raylet (src/ray/raylet/) and GCS
(src/ray/gcs/). Composed of:

- GCS server process (ray_tpu/_private/gcs/): node/actor/KV/job tables,
  pubsub, health checks.
- Raylet process per node (ray_tpu/_private/raylet/): worker pool, local
  scheduler with TPU-aware resources, lease protocol.
- Shared-memory object store (src/object_store/, C++): plasma-equivalent.
- Worker processes executing tasks/actors.

Under construction — milestone 2 of round 1.
"""

from __future__ import annotations


class ClusterRuntime:
    @classmethod
    def create(cls, **kwargs):
        raise NotImplementedError(
            "Cluster mode is under construction in this round; "
            "use ray_tpu.init(local_mode=True) meanwhile."
        )
