"""ClusterRuntime — the real multi-process execution backend.

Reference analogue: the Cython CoreWorker (python/ray/_raylet.pyx:2851) over
src/ray/core_worker/, plus python/ray/_private/worker.py connect() :2026.

Composition:
- GCS server process (ray_tpu/_private/gcs/): node/actor/KV/job/PG tables,
  health checks, actor scheduling.
- Raylet process per node (ray_tpu/_private/raylet/): worker pool, local
  scheduler with TPU-aware resources, lease protocol, bundle 2PC.
- Native shared-memory object store (src/object_store/store.cc).
- Worker processes (ray_tpu/_private/workers/default_worker.py).
- This driver-side runtime: a CoreWorker connected as the driver.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.ids import JobID
from ray_tpu._private.node import Node
from ray_tpu._private.rpc import RpcClient, clear_client_cache

logger = logging.getLogger(__name__)


class ClusterRuntime(CoreWorker):
    """CoreWorker in driver mode + lifecycle of locally-started node procs."""

    def __init__(self, node: Optional[Node], gcs_addr, raylet_addr, store_socket, node_id, job_id):
        self._node = node
        super().__init__(
            gcs_addr=gcs_addr,
            raylet_addr=raylet_addr,
            store_socket=store_socket,
            node_id=node_id,
            job_id=job_id,
            is_driver=True,
        )

    @classmethod
    def create(
        cls,
        address: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        namespace: Optional[str] = None,
        dashboard: bool = False,
    ) -> "ClusterRuntime":
        if address in (None, "local"):
            node = Node(
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
                object_store_memory=object_store_memory,
            )
            node.start()
            gcs_addr = node.gcs_addr
            raylet_addr = node.raylet_addr
            store_socket = node.store_socket
            node_id = node.node_id
        else:
            # connect to an existing cluster: address = "host:port" of GCS
            node = None
            host, port_s = address.rsplit(":", 1)
            gcs_addr = (host, int(port_s))
            gcs = RpcClient(gcs_addr[0], gcs_addr[1])
            nodes = gcs.call_retrying("GetAllNodeInfo")
            alive = [n for n in nodes if n["Alive"]]
            # prefer the head node: the driver shares its object store
            local = next((n for n in alive if n.get("IsHead")), alive[0] if alive else None)
            if local is None:
                raise RuntimeError("no alive nodes in cluster")
            raylet_addr = (local["NodeManagerAddress"], local["NodeManagerPort"])
            store_socket = local["ObjectStoreSocketName"]
            node_id = local["NodeID"]
            gcs.close()

        # register the driver's job
        runtime = cls(node, gcs_addr, raylet_addr, store_socket, node_id, JobID.from_int(0))
        reply = runtime.gcs.call_retrying("RegisterJob", driver_addr=runtime.address, metadata={})
        runtime.job_id = JobID.from_int(reply["job_id_int"])
        return runtime

    def shutdown(self) -> None:
        try:
            self.gcs.call("MarkJobFinished", job_id=self.job_id.hex(), timeout=5)
        except Exception:  # GCS may already be gone — finish local teardown
            logger.debug("MarkJobFinished failed at shutdown", exc_info=True)
        super().shutdown()
        clear_client_cache()
        if self._node is not None:
            self._node.stop()
