"""Flag/config system for ray_tpu.

TPU-native equivalent of the reference's ``RAY_CONFIG(type, name, default)``
macro table (reference: src/ray/common/ray_config_def.h:18 — 244 flags,
overridable via ``RAY_<name>`` env vars and a ``_system_config`` dict at init).

Here every flag is declared once in ``_DEFINITIONS`` with a type and default,
is overridable via the ``RAY_TPU_<NAME>`` environment variable, and can be
overridden programmatically via ``RayTpuConfig.initialize(system_config)``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Tuple


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


# name -> (type, default)
_DEFINITIONS: Dict[str, Tuple[type, Any]] = {
    # --- event loop / rpc ---
    "rpc_connect_timeout_s": (float, 10.0),
    "rpc_call_timeout_s": (float, 60.0),
    # actor __init__ runs user code (model builds, framework imports)
    "actor_creation_timeout_s": (float, 600.0),
    "rpc_retry_base_delay_ms": (int, 100),
    "rpc_retry_max_delay_ms": (int, 5000),
    "rpc_max_retries": (int, 5),
    "event_loop_slow_handler_ms": (int, 100),
    # --- chaos / fault injection (reference: src/ray/rpc/rpc_chaos.h:8,
    # src/ray/asio/asio_chaos.h) ---
    "testing_rpc_failure": (str, ""),  # "method=prob" comma list
    "testing_asio_delay_us": (str, ""),
    # --- gcs ---
    "gcs_port": (int, 0),  # 0 = pick free port
    "gcs_storage_path": (str, ""),  # "" = in-memory; else file-backed persistence
    "gcs_health_check_period_ms": (int, 1000),
    "gcs_health_check_timeout_ms": (int, 5000),
    # 20 missed beats (~20s) before a node is declared dead. 5 was too
    # twitchy on an oversubscribed 1-core host: a 2,000-actor burst
    # starves the raylet process of CPU long enough to gap heartbeats
    # >10s, and one false death cascades (every actor on the node
    # fails — observed killing the combined scale phase). The reference
    # defaults to ~30s of missed heartbeats; tests that kill nodes
    # budget >=30s for detection, so 20s keeps their margin.
    "gcs_health_check_failure_threshold": (int, 20),
    "gcs_pubsub_poll_timeout_s": (float, 30.0),
    # --- graceful node drain (reference: DrainNode with a deadline and
    # DRAIN_NODE_REASON_PREEMPTION in gcs_service.proto; TPU preemption
    # notices give the whole slice a short window to quiesce) ---
    "drain_deadline_default_s": (float, 30.0),
    # how long past its deadline a DRAINING node may sit before the GCS
    # watchdog force-completes the drain (marks it dead) — bounds the
    # "node stuck DRAINING forever" failure mode across GCS restarts
    "drain_watchdog_grace_s": (float, 5.0),
    # --- raylet / scheduler ---
    "raylet_heartbeat_period_ms": (int, 500),
    "worker_lease_timeout_ms": (int, 30000),
    "worker_pool_prestart_workers": (bool, False),
    # fork workers from a warmed zygote process instead of cold
    # interpreter starts (workers/zygote.py)
    "worker_zygote_enabled": (bool, True),
    "worker_idle_timeout_s": (float, 60.0),
    "max_workers_per_node": (int, 64),
    "scheduler_top_k_fraction": (float, 0.2),
    "scheduler_top_k_absolute": (int, 1),
    "scheduler_spread_threshold": (float, 0.5),
    "worker_startup_timeout_s": (float, 60.0),
    # OOM worker killing (reference: raylet memory monitor +
    # worker_killing_policy_group_by_owner.h); >= 1.0 disables
    "memory_usage_threshold": (float, 0.97),
    "memory_monitor_period_s": (float, 1.0),
    # test hook: read the fake memory pct from this file instead of
    # psutil (lets tests drive pressure up and down deterministically)
    "testing_memory_pct_file": (str, ""),
    # --- object store ---
    "object_store_memory_bytes": (int, 2 * 1024**3),
    "object_store_socket": (str, ""),
    "object_spilling_dir": (str, ""),
    "object_store_full_delay_ms": (int, 100),
    "object_store_inline_max_bytes": (int, 100 * 1024),
    "object_pull_chunk_bytes": (int, 8 * 1024**2),
    # --- tasks ---
    "task_max_retries_default": (int, 3),
    # how long a submitter keeps an idle granted lease warm before
    # returning it to the raylet. A sync small-task loop previously paid
    # RequestWorkerLease + SetLeaseContext + ReturnWorkerLease around
    # EVERY PushTask (~4 control RPCs per call); with keep-alive the warm
    # path is one worker RPC. 0 restores return-on-idle.
    "worker_lease_keepalive_s": (float, 0.5),
    # queued same-class tasks pushed to a leased worker per RPC roundtrip
    # (1 = the reference's one-PushTask-per-task behavior)
    "task_push_batch_size": (int, 32),
    # producer pauses when this many yields sit unconsumed at the caller
    # (reference: generator_backpressure_num_objects)
    "streaming_generator_buffer_size": (int, 256),
    "actor_max_restarts_default": (int, 0),
    "max_pending_lease_requests_per_class": (int, 10),
    # how long a caller keeps resending an un-acked actor task while the
    # actor is unreachable/restarting before failing it
    "actor_task_resend_timeout_s": (float, 60.0),
    # how long a caller waits for a PENDING actor to come ALIVE before
    # its queued task fails (actor __init__ can be slow; large actor
    # bursts queue behind each other)
    "actor_wait_alive_timeout_s": (float, 180.0),
    # GCS-side deadline for finding+leasing a worker for a PENDING actor
    # (the whole creation backlog of a large burst queues behind it)
    "actor_schedule_timeout_s": (float, 300.0),
    # in-flight actor creations (lease+spawn+CreateActor pipelines) the
    # GCS runs concurrently — admission control against thundering-herd
    # collapse on hosts with few cores
    "actor_creation_concurrency": (int, 48),
    # owner-side sweep dropping borrowers whose process died without
    # deregistering (reference: WaitForRefRemoved, reference_counter.h:44)
    "borrower_liveness_period_s": (float, 30.0),
    # --- tpu ---
    "tpu_chips_per_host_default": (int, 4),
    "megascale_port": (int, 8081),
    "jax_coordinator_port": (int, 8476),
    # --- logging ---
    "log_dir": (str, ""),
    "log_to_driver": (bool, True),
}


class _Config:
    """Singleton holding resolved config values."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = {}
        self._load_defaults()

    def _load_defaults(self) -> None:
        for name, (typ, default) in _DEFINITIONS.items():
            env = os.environ.get("RAY_TPU_" + name.upper())
            if env is not None:
                if typ is bool:
                    self._values[name] = _parse_bool(env)
                else:
                    self._values[name] = typ(env)
            else:
                self._values[name] = default

    def initialize(self, system_config: Dict[str, Any] | None = None) -> None:
        """Apply a programmatic override dict (like Ray's _system_config)."""
        with self._lock:
            self._load_defaults()
            if system_config:
                for k, v in system_config.items():
                    if k not in _DEFINITIONS:
                        raise ValueError(f"Unknown config flag: {k}")
                    typ = _DEFINITIONS[k][0]
                    self._values[k] = _parse_bool(v) if typ is bool and isinstance(v, str) else typ(v)

    def to_json(self) -> str:
        with self._lock:
            return json.dumps(self._values)

    def from_json(self, payload: str) -> None:
        with self._lock:
            self._values.update(json.loads(payload))

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None


config = _Config()
