"""CoreRuntime — the interface every execution backend implements.

The public API (``ray_tpu.get/put/remote/...``) talks only to this
interface. Two backends exist:

- ``LocalModeRuntime`` (ray_tpu/_private/local_mode.py): in-process, for
  ``init(local_mode=True)`` and unit tests — reference analogue:
  python/ray/_private/worker.py LOCAL_MODE.
- ``ClusterRuntime`` (ray_tpu/_private/cluster_runtime.py): the real
  multi-process runtime (GCS + raylet + shared-memory object store +
  worker processes) — reference analogue: the Cython CoreWorker
  (python/ray/_raylet.pyx:2851) over src/ray/core_worker/.
"""

from __future__ import annotations

import abc
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import SchedulingStrategy


@dataclass
class TaskOptions:
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    name: str = ""


@dataclass
class ActorOptions:
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    get_if_exists: bool = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    # Reference semantics (actor.py:616+ docs): an actor whose num_cpus was
    # NOT specified uses 1 CPU for *scheduling* its creation but holds 0 CPU
    # while alive — otherwise long-lived actors starve task leases.
    cpu_scheduling_only: bool = True


def normalize_resources(
    num_cpus: Optional[float],
    num_gpus: Optional[float],
    num_tpus: Optional[float],
    resources: Optional[Dict[str, float]],
    memory: Optional[float] = None,
    default_cpus: float = 1.0,
) -> Dict[str, float]:
    """Fold the num_cpus/num_tpus/resources keywords into one resource dict.

    TPU is a first-class resource here (the reference bolts it on through
    python/ray/_private/accelerators/tpu.py:345); ``num_gpus`` is accepted
    for API compatibility and maps to the "GPU" key.
    """
    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus) if num_cpus is not None else default_cpus
    if num_gpus:
        out["GPU"] = float(num_gpus)
    if num_tpus:
        out["TPU"] = float(num_tpus)
    if memory:
        out["memory"] = float(memory)
    # drop zero entries
    return {k: v for k, v in out.items() if v}


class CoreRuntime(abc.ABC):
    @abc.abstractmethod
    def put(self, value: Any) -> ObjectRef: ...

    @abc.abstractmethod
    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]: ...

    @abc.abstractmethod
    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
        fetch_local: bool = True,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]: ...

    @abc.abstractmethod
    def submit_task(
        self, remote_function, args: tuple, kwargs: dict, opts: TaskOptions
    ) -> List[ObjectRef]: ...

    @abc.abstractmethod
    def create_actor(self, actor_class, args: tuple, kwargs: dict, opts: ActorOptions): ...

    @abc.abstractmethod
    def submit_actor_task(
        self, handle, method_name: str, args: tuple, kwargs: dict, opts: TaskOptions
    ) -> List[ObjectRef]: ...

    @abc.abstractmethod
    def kill_actor(self, actor_id, no_restart: bool = True) -> None: ...

    @abc.abstractmethod
    def cancel(self, ref: ObjectRef, force: bool = False, recursive: bool = True) -> None: ...

    @abc.abstractmethod
    def as_future(self, ref: ObjectRef) -> Future: ...

    @abc.abstractmethod
    def free_object(self, oid) -> None: ...

    @abc.abstractmethod
    def get_actor(self, name: str, namespace: Optional[str] = None): ...

    @abc.abstractmethod
    def cluster_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def available_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def nodes(self) -> List[Dict[str, Any]]: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    # Placement groups — implemented by cluster runtime; local mode fakes them.
    def create_placement_group(self, bundles, strategy, name=""):
        raise NotImplementedError

    def remove_placement_group(self, pg_id) -> None:
        raise NotImplementedError

    def placement_group_ready(self, pg_id, timeout=None) -> bool:
        raise NotImplementedError
