"""CoreWorker — per-process runtime for the multi-process cluster backend.

Reference: src/ray/core_worker/core_worker.h:168 (CoreWorker) and its
submodules: NormalTaskSubmitter (task_submission/normal_task_submitter.h:87,
lease caching + OnWorkerIdle), TaskManager (task_manager.h:195 — retries,
completion), ReferenceCounter (reference_counter.h:44), memory store
(memory_store.h:48), plasma provider (plasma_store_provider.h:94),
ActorTaskSubmitter (actor_task_submitter.h:69 — seqno ordering).

Every process (driver or executor worker) owns one CoreWorker: it serves
owner RPCs (GetObject — the ownership model's data path), submits tasks via
raylet leases, and resolves objects from {memory store, shared-memory store,
remote owner}.

Object entry formats in the owner memory store:
    ("inline", bytes)   — serialized value (may deserialize to RayTaskError)
    ("plasma", node_id) — sealed in the node's shared-memory store
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
import time
import uuid
import concurrent.futures
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import debug_locks
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import config
from ray_tpu._private.core import ActorOptions, CoreRuntime, TaskOptions
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store.client import StoreClient
from ray_tpu._private.rpc import (
    EventLoopThread,
    RemoteError,
    RpcClient,
    RpcConnectionError,
    RpcServer,
    get_client,
)
from ray_tpu._private.serialization import deserialize, serialize
from ray_tpu._private.task_spec import (
    FunctionDescriptor,
    SchedulingStrategy,
    TaskArg,
    TaskSpec,
    TaskType,
)
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    ObjectStoreFullError,
    RayActorError,
    RayTaskError,
    TaskCancelledError,
    WorkerCrashedError,
)
from ray_tpu.observability import dump as obs_dump
from ray_tpu.observability import events as obs_events
from ray_tpu.observability import timeline as obs_timeline
from ray_tpu.observability import tracing as obs_tracing

logger = logging.getLogger(__name__)


def _task_latency_histogram():
    """Submit→completion latency histogram (caller-side), merged into the
    util/metrics.py scrape endpoint. Import stays lazy so the metrics
    pusher thread only exists in processes that complete tasks."""
    from ray_tpu.util.metrics import get_histogram

    return get_histogram(
        "ray_tpu_task_latency_s",
        description="Task submit-to-completion latency",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
        tag_keys=("kind",),
    )


class _InfeasibleStrategyError(Exception):
    """A hard scheduling-strategy constraint can never be satisfied."""


class _TransientSchedulingError(Exception):
    """The node view is unavailable right now (GCS blip) — retry, don't
    fail the tasks."""


class _LeaseEntry:
    __slots__ = ("lease_id", "worker_addr", "busy", "last_used",
                 "raylet_addr", "warm", "drain_final_pushes")

    def __init__(
        self,
        lease_id: str,
        worker_addr: Tuple[str, int],
        raylet_addr: Optional[Tuple[str, int]] = None,
    ):
        self.lease_id = lease_id
        self.worker_addr = worker_addr
        # which raylet granted this lease (spillback may land on a remote
        # node); ReturnWorkerLease must go back to the same raylet
        self.raylet_addr = raylet_addr
        self.busy = False
        self.last_used = time.monotonic()
        # the lease already completed at least one push: the worker was
        # healthy AFTER grant. A ConnectionError on a warm lease means
        # the keepalive cache outlived its worker (SIGKILL, node drain)
        # — that is a lease-layer fault, retried for FREE rather than
        # burning the task's max_retries (reference: lease-level retries
        # in normal_task_submitter never charge the app retry budget)
        self.warm = False
        # recall-override pushes already spent on this (draining) lease
        # — see CoreWorker._handle_lease_recalled
        self.drain_final_pushes = 0


class _ActorDispatcher:
    """Event-driven per-actor task dispatch on the core worker's io loop
    (reference: ActorTaskSubmitter, actor_task_submitter.cc:167 SubmitTask
    / :534 SendPendingTasks — every actor's submit queue is driven from one
    io_context, with actor state PUSHED to the submitter, not polled).

    No thread per actor: ``submit()`` appends to the send queue and wakes
    an asyncio sender task shared per (caller, actor). The sender drains
    the queue into ORDERED batches — one ``PushActorTasks`` RPC carries up
    to ``_MAX_BATCH`` payloads — so a burst of small calls costs one
    enqueue-ack round-trip per batch, not per call. Per-caller ordering
    holds because batch N's enqueue ack is awaited before batch N+1 is
    sent and the worker enqueues a batch in list order; no seqno windows,
    so ordering survives actor restarts. Execution results come back
    asynchronously via the caller's ``ActorTasksDone`` RPC.

    While tasks are pending, ONE long-poll ``WaitActorUpdate`` watcher per
    actor (GCS pushes state changes to it) detects death/restart the
    moment it is published — replacing the old 1 s ``GetActorInfo``
    polling threads; the same watcher requeries old pending tasks to
    recover lost result pushes.
    """

    _MAX_BATCH = 64
    # pending tasks older than this on a healthy actor are re-queried at the
    # worker (covers a lost ActorTasksDone delivery)
    _REQUERY_AGE_S = 10.0

    def __init__(self, core: "CoreWorker", aid: str):
        self.core = core
        self.aid = aid
        self._dead = False
        self._closed = False
        self._state_lock = threading.Lock()
        self._items: List[Tuple[dict, List[ObjectID]]] = []
        self._loop = core.loop_thread.loop
        self._wake = asyncio.Event()
        self._watcher: Optional[asyncio.Task] = None
        self._sender = asyncio.run_coroutine_threadsafe(
            self._run(), self._loop)

    @property
    def alive(self) -> bool:
        return not (self._dead or self._closed or self._sender.done())

    def submit(self, payload: dict, return_oids: List[ObjectID]) -> None:
        with self._state_lock:
            if not self._dead and not self._closed:
                self._items.append((payload, return_oids))
                self._loop.call_soon_threadsafe(self._wake.set)
                return
        self.core._fail_actor_task(
            TaskID(payload["task_id"]), return_oids,
            ActorDiedError(f"Actor {self.aid[:12]} is dead"),
        )

    def stop(self) -> None:
        self._closed = True
        try:
            self._loop.call_soon_threadsafe(self._wake.set)
        except RuntimeError:
            pass  # loop already closed at shutdown

    # -- sender (io loop) ----------------------------------------------
    async def _run(self) -> None:
        try:
            while True:
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=5.0)
                except asyncio.TimeoutError:
                    pass
                self._wake.clear()
                if self._closed or self.core._shutdown:
                    # fail anything still queued — a silent exit would
                    # leave the tasks' return objects unresolved forever
                    with self._state_lock:
                        leftovers, self._items = self._items, []
                    err = RayActorError(
                        f"caller shut down before task reached actor "
                        f"{self.aid[:12]}")
                    for payload, oids in leftovers:
                        self.core._fail_actor_task(
                            TaskID(payload["task_id"]), oids, err)
                    return
                with self._state_lock:
                    items, self._items = self._items, []
                pos = 0
                while pos < len(items) and not self._dead:
                    batch = items[pos:pos + self._MAX_BATCH]
                    try:
                        await self._send_batch(batch)
                    except BaseException as e:  # noqa: BLE001 — must survive
                        logger.exception(
                            "actor dispatch failed for %s", self.aid[:12])
                        for payload, oids in batch:
                            self.core._fail_actor_task(
                                TaskID(payload["task_id"]), oids,
                                RayActorError(
                                    f"Failed to dispatch task to actor "
                                    f"{self.aid[:12]}: {e!r}"))
                    pos += self._MAX_BATCH
                if self._dead:
                    self._retire(items[pos:])
                    return
                # one persistent watcher per dispatcher, started at the
                # first send — NOT per pending burst, which would cost a
                # GCS round-trip per call on the sync path
                if items and (self._watcher is None
                              or self._watcher.done()):
                    self._watcher = asyncio.ensure_future(self._watch())
        finally:
            if self._watcher is not None and not self._watcher.done():
                self._watcher.cancel()

    def _has_pending(self) -> bool:
        with self.core._actor_pending_lock:
            return any(
                info["aid"] == self.aid
                for info in self.core._pending_actor_tasks.values()
            )

    def _retire(self, leftovers) -> None:
        """Actor is DEAD: fail queued work and deregister."""
        with self._state_lock:
            self._dead = True
            items = list(leftovers) + self._items
            self._items = []
        err = ActorDiedError(f"Actor {self.aid[:12]} is dead")
        for payload, oids in items:
            self.core._fail_actor_task(TaskID(payload["task_id"]), oids, err)
        with self.core._actor_disp_lock:
            if self.core._actor_dispatchers.get(self.aid) is self:
                del self.core._actor_dispatchers[self.aid]

    async def _send_batch(
        self, batch: List[Tuple[dict, List[ObjectID]]],
    ) -> None:
        deadline = time.monotonic() + config.actor_task_resend_timeout_s

        def _fail_all(err: Exception) -> None:
            for payload, oids in batch:
                self.core._fail_actor_task(
                    TaskID(payload["task_id"]), oids, err)

        while True:
            try:
                addr = await self.core._resolve_actor_async(self.aid)
            except ActorDiedError as e:
                self._dead = True
                _fail_all(e)
                return
            except (ActorUnavailableError, RayActorError) as e:
                _fail_all(e)
                return
            except Exception as e:  # noqa: BLE001 — e.g. GCS briefly down
                if time.monotonic() > deadline:
                    _fail_all(RayActorError(
                        f"Could not resolve actor {self.aid[:12]}: {e}"))
                    return
                await asyncio.sleep(0.5)
                continue
            # register pending BEFORE the push: the done RPC can arrive
            # before the enqueue ack returns
            now = time.monotonic()
            with self.core._actor_pending_lock:
                for payload, oids in batch:
                    self.core._pending_actor_tasks[
                        TaskID(payload["task_id"])] = {
                        "aid": self.aid,
                        "return_oids": oids,
                        "addr": addr,
                        "method": payload.get("method_name", "actor_task"),
                        "ts": now,
                        "submit_ts": payload.get("submit_ts", 0.0),
                    }
            try:
                reply = await get_client(addr).acall(
                    "PushActorTasks",
                    payloads=[p for p, _ in batch], timeout=30,
                )
            except (RpcConnectionError, ConnectionError, OSError,
                    TimeoutError) as e:
                self._unregister(batch)
                # Planned loss first: if the GCS already moved this actor
                # off the address we pushed to (node drain migrates
                # actors BEFORE their workers die), the dead worker had
                # stopped accepting — the batch was never enqueued there,
                # so resending to the new incarnation keeps at-most-once.
                if await self._moved_by_drain(addr):
                    self.core._invalidate_actor_addr(self.aid, addr)
                    if time.monotonic() > deadline:
                        _fail_all(RayActorError(
                            f"Actor {self.aid[:12]} not reachable at a "
                            f"stable address"))
                        return
                    await asyncio.sleep(0.2)
                    continue
                # Unplanned: the push may or may not have reached the
                # worker before the connection broke, so resending could
                # execute it twice. Actor tasks are at-most-once
                # (reference: actor tasks are not retried unless
                # max_task_retries > 0) — report the fault (triggers
                # restart per max_restarts) and fail THIS batch; queued
                # successors will reach the new incarnation.
                await self.core._report_actor_fault_async(
                    self.aid, addr, str(e))
                _fail_all(RayActorError(
                    f"Actor {self.aid[:12]} became unreachable while a "
                    f"task batch was being delivered: {e}"))
                return
            if not reply.get("accepted"):
                # live worker without this actor: stale address (restart)
                self._unregister(batch)
                self.core._invalidate_actor_addr(self.aid, addr)
                if time.monotonic() > deadline:
                    _fail_all(RayActorError(
                        f"Actor {self.aid[:12]} not reachable at a "
                        f"stable address"))
                    return
                await asyncio.sleep(0.2)
                continue
            return

    def _unregister(self, batch) -> None:
        with self.core._actor_pending_lock:
            for payload, _ in batch:
                self.core._pending_actor_tasks.pop(
                    TaskID(payload["task_id"]), None)

    async def _moved_by_drain(self, pushed_addr: Tuple[str, int]) -> bool:
        """True when the GCS has already restarted this actor away from
        ``pushed_addr`` BECAUSE ITS NODE DRAINED — i.e. the address we
        pushed to was a planned casualty. Requires the drain cause, not
        just a state change: a crash can also reach the GCS (raylet
        death report) before we process our own ConnectionError, and
        resending after a crash could double-execute an at-most-once
        actor task. Drain is safe: the old instance stopped ACCEPTING
        before the restart was published, so a connection-failed push
        was never enqueued there."""
        try:
            info = await self.core.gcs.acall(
                "GetActorInfo", actor_id=self.aid, timeout=10)
        except Exception:  # noqa: BLE001
            return False
        if not info or "draining" not in (info.get("death_cause") or ""):
            return False
        if info.get("state") == "RESTARTING":
            return True
        cur = tuple(info["worker_addr"]) if info.get("worker_addr") else None
        return info.get("state") == "ALIVE" and cur is not None \
            and cur != tuple(pushed_addr)

    # -- watcher (io loop): pushed actor state + lost-result recovery ---
    async def _watch(self) -> None:
        """Wakes on THIS actor's state changes via the process-wide
        actor-state hub — one shared GCS ``Subscribe`` long-poll serves
        every dispatcher in the process (the per-actor WaitActorUpdate
        design cost N/5 RPC/s with N actors pending; a 2,000-actor burst
        saturated the control plane on polls alone). GetActorInfo runs
        only when the hub reports a change; the lost-push requery sweep
        runs off the local clock with the cached address."""
        ev = self.core._actor_hub.watch(self.aid)
        try:
            # one unconditional fetch: a state change BEFORE the hub
            # registration must not be missed
            changed = True
            while not (self._closed or self._dead or self.core._shutdown):
                with self.core._actor_pending_lock:
                    mine = {
                        t: i
                        for t, i in self.core._pending_actor_tasks.items()
                        if i["aid"] == self.aid
                    }
                current = None
                if changed:
                    try:
                        info = await self.core.gcs.acall(
                            "GetActorInfo", actor_id=self.aid, timeout=15)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — GCS blip; retry
                        await asyncio.sleep(1.0)
                        continue
                    if info is None or info["state"] == "DEAD":
                        cause = (info or {}).get(
                            "death_cause", "actor no longer exists")
                        for t, i in mine.items():
                            self.core._fail_actor_task(
                                t, i["return_oids"],
                                ActorDiedError(
                                    f"Actor {self.aid[:12]} died: "
                                    f"{cause}"))
                        self._dead = True
                        self._retire([])
                        return
                    current = tuple(info["worker_addr"]) \
                        if info.get("worker_addr") else None
                else:
                    cached = self.core._actor_addr_cache.get(self.aid)
                    current = cached[0] if cached else None
                now = time.monotonic()
                for t, i in mine.items():
                    # enqueued on an incarnation that is gone: before
                    # declaring it lost, ask the OLD worker — a drained
                    # node's actor finishes its accepted tasks before the
                    # restart is published, so the result is usually
                    # sitting in its cache (or the done push already
                    # landed); only an unreachable/amnesiac old worker
                    # fails the task. Re-checked on the periodic sweep
                    # too: a "running" reply from the old incarnation
                    # must not park the task forever if that worker then
                    # dies without another state event.
                    stale = now - i.get("ts", now) > self._REQUERY_AGE_S
                    if i["addr"] != current and (changed or stale):
                        await self._requery(t, i, i["addr"],
                                            fail_unreachable=True)
                    elif current is not None and i["addr"] == current \
                            and stale:
                        # healthy actor, old pending task: the result
                        # push may have been lost — ask the worker
                        await self._requery(t, i, current)
                if not mine and not self._has_pending():
                    # idle: deregister from the hub (40k idle actors must
                    # cost zero RPC); _run re-arms us at the next send
                    return
                changed = False
                try:
                    await asyncio.wait_for(ev.wait(),
                                           timeout=self._REQUERY_AGE_S)
                    changed = True
                    ev.clear()
                except asyncio.TimeoutError:
                    pass
        finally:
            self.core._actor_hub.unwatch(self.aid, ev)

    async def _requery(
        self, tid: TaskID, info: dict, addr: Tuple[str, int],
        fail_unreachable: bool = False,
    ) -> None:
        try:
            reply = await get_client(addr).acall(
                "QueryActorTaskResult",
                actor_id=self.aid,
                task_id_bin=tid.binary(),
                timeout=10,
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            if fail_unreachable:
                # the incarnation this task was enqueued on is gone AND
                # unreachable — the task is lost for real
                self.core._fail_actor_task(
                    tid, info["return_oids"],
                    RayActorError(
                        f"Actor {self.aid[:12]} restarted; task "
                        f"{tid.hex()[:12]} was lost"))
            return  # connection-level failures are the watcher's job
        status = reply.get("status")
        if status == "done":
            self.core._handle_actor_task_done(
                tid.binary(), reply["returns"],
                streaming_done=reply.get("streaming_done"),
                stream_error=reply.get("stream_error"),
                failed=bool(reply.get("failed")),
            )
        elif status == "unknown":
            self.core._fail_actor_task(
                tid, info["return_oids"],
                RayActorError(
                    f"Actor {self.aid[:12]} has no record of task "
                    f"{tid.hex()[:12]}; it was lost"),
            )
        # "running": leave it pending


class _ActorStateHub:
    """Process-wide fan-out of GCS actor-state events (reference:
    src/ray/pubsub — every subscriber shares the publisher's channel;
    the reference never opens one poll per actor, and at 2k+ actors
    neither can we). One ``Subscribe("actor_state")`` long-poll feeds
    per-actor asyncio.Events; the loop runs only while someone is
    watching and dies when the last watcher leaves."""

    def __init__(self, core: "CoreWorker"):
        self.core = core
        self._events: Dict[str, set] = {}  # aid -> set of Events
        # freshest event payload per WATCHED actor ({state, version,
        # worker_addr, death_cause}): the event itself resolves the
        # actor, so a woken waiter usually needs no GetActorInfo
        # round-trip. Pruned with the watcher set — unwatched actors'
        # events are never recorded.
        self.last_event: Dict[str, dict] = {}
        self._seq = 0
        self._task: Optional[asyncio.Task] = None

    def watch(self, aid: str) -> asyncio.Event:
        """io-loop only. Returns an Event set on every state change of
        ``aid`` (coalesced; consumer clears)."""
        ev = asyncio.Event()
        self._events.setdefault(aid, set()).add(ev)
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())
        return ev

    def unwatch(self, aid: str, ev: asyncio.Event) -> None:
        s = self._events.get(aid)
        if s is not None:
            s.discard(ev)
            if not s:
                del self._events[aid]
                self.last_event.pop(aid, None)

    async def _loop(self) -> None:
        while self._events and not self.core._shutdown:
            after = self._seq
            try:
                rep = await self.core.gcs.acall(
                    "Subscribe", channel="actor_state",
                    after_seq=after, timeout_s=30.0, timeout=45)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — GCS blip/restart
                await asyncio.sleep(1.0)
                # a restarted GCS renumbers its pubsub sequence; resync
                # and wake everyone so they re-fetch their actor's state
                self._seq = 0
                for s in self._events.values():
                    for ev in s:
                        ev.set()
                continue
            self._seq = rep.get("next_seq", self._seq)
            if after < rep.get("dropped_floor", 0):
                # the publisher's ring evicted events past our cursor:
                # anything between after and the floor is gone, and a
                # missed DEAD/restart transition would hang its watcher's
                # pending tasks forever — wake EVERY watcher so each
                # re-fetches its actor's state (changed=True path)
                self._seq = max(self._seq, rep["dropped_floor"])
                for s in self._events.values():
                    for ev in s:
                        ev.set()
            for _seqno, aid, payload in rep.get("events", ()):
                if isinstance(payload, dict) and \
                        payload.get("state") != "ALIVE":
                    # the cached resolve address is stale the moment the
                    # actor leaves ALIVE (drain migration, restart): drop
                    # it so new submits block on the fresh address
                    # instead of pushing at the doomed incarnation
                    self.core._actor_addr_cache.pop(aid, None)
                watchers = self._events.get(aid)
                if not watchers:
                    continue
                if isinstance(payload, dict):
                    prev = self.last_event.get(aid)
                    if prev is None or payload.get("version", 0) >= \
                            prev.get("version", 0):
                        self.last_event[aid] = payload
                for ev in watchers:
                    ev.set()


class CoreWorker(CoreRuntime):
    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        raylet_addr: Tuple[str, int],
        store_socket: str,
        node_id: str,
        job_id: JobID,
        is_driver: bool,
        worker_id_hex: Optional[str] = None,
    ):
        self.gcs_addr = gcs_addr
        self.raylet_addr = raylet_addr
        self.node_id = node_id
        self.job_id = job_id
        self.is_driver = is_driver
        self.worker_id_hex = worker_id_hex or uuid.uuid4().hex

        # ONE io loop per process (reference: the core worker's
        # io_context drives clients, server, and actor submitters alike):
        # sharing the global loop keeps get_client() connections, the
        # owner server, and the actor dispatchers loop-affine — a second
        # loop would cost two cross-thread handoffs per actor-task send
        self.loop_thread = EventLoopThread.get_global()
        self.gcs = RpcClient(gcs_addr[0], gcs_addr[1], self.loop_thread)
        self.raylet = RpcClient(raylet_addr[0], raylet_addr[1], self.loop_thread)
        self.plasma = StoreClient(store_socket)
        self.memory_store = MemoryStore()
        # node_id -> raylet addr, for pulling remote plasma objects
        # (owner-based location directory: the owner's memory-store entry
        # names the node; this maps it to that node's object manager)
        self._node_addrs: Dict[str, Tuple[str, int]] = {}
        self._node_addrs_lock = debug_locks.maybe_wrap(
            threading.Lock(), "core_worker.CoreWorker._node_addrs_lock")

        # owner RPC server (GetObject / WaitObject / health). Handlers
        # that only touch the memory store / pending tables register
        # inline: they run on the io loop with no executor handoff —
        # the result-delivery hop of every warm actor call rides these.
        self.server = RpcServer(name=f"core-{self.worker_id_hex[:8]}")
        # single-item endpoint kept for debugging/compat (the runtime
        # itself uses the batched GetObjectsStatus) — raycheck: disable=RC003
        self.server.register("GetObject", self._handle_get_object,
                             inline=True)
        self.server.register("GetObjectsStatus",
                             self._handle_get_objects_status, inline=True)
        self.server.register("WaitObject", self._handle_wait_object)
        self.server.register("RecoverObject", self._handle_recover_object)
        self.server.register("AddBorrower", self._handle_add_borrower,
                             inline=True)
        self.server.register("RemoveBorrower", self._handle_remove_borrower,
                             inline=True)
        # single-item fallback of ActorTasksDone — raycheck: disable=RC003
        self.server.register("ActorTaskDone", self._handle_actor_task_done,
                             inline=True)
        self.server.register("ActorTasksDone", self._handle_actor_tasks_done,
                             inline=True)
        self.server.register("NormalTaskDone", self._handle_normal_task_done)
        self.server.register("StreamingYield", self._handle_streaming_yield,
                             inline=True)
        self.server.register("StreamingDone", self._handle_streaming_done,
                             inline=True)
        self.server.register("StreamingCredit",
                             self._handle_streaming_credit, inline=True)
        self.server.register("Ping", lambda: "pong", inline=True)
        # flight-recorder: the GCS fans failure dumps out to every
        # process it can reach; drivers and workers alike answer here
        self.server.register("DebugDump", self._handle_debug_dump)
        self.server.start(self.loop_thread)
        self.address: Tuple[str, int] = (self.server.host, self.server.port)
        obs_dump.install("driver" if is_driver else "worker")

        # scheduling-strategy state
        self._node_view_cache: Optional[Tuple[float, List[dict]]] = None
        self._spread_rr = -1

        # task submission state
        self._lock = debug_locks.maybe_wrap(
            threading.Lock(), "core_worker.CoreWorker._lock")
        self._leases: Dict[Any, List[_LeaseEntry]] = {}  # scheduling_class -> entries
        self._lease_requests_inflight: Dict[Any, int] = {}
        # keep-alive sweeper for idle granted leases (io-loop task,
        # armed lazily on the first idle lease)
        self._lease_sweeper: Optional[asyncio.Task] = None
        # deques: 100k queued tasks must pop O(1), not O(n)
        self._task_queue: Dict[Any, Any] = {}  # sc -> deque[TaskSpec]
        self._pending_tasks: Dict[TaskID, Dict[str, Any]] = {}
        # worker_addr -> function_keys whose bytes that worker has cached
        self._fns_shipped: Dict[Tuple[str, int], set] = {}

        # streaming generators: task_id -> _StreamState (task_manager.cc:778)
        self._streams: Dict[TaskID, Any] = {}

        # Lineage (reference: task_manager.h:195 lineage pinning +
        # object_recovery_manager.h:41). For every completed normal task
        # with in-scope plasma returns we keep the spec — arg refs stay
        # pinned — so a lost object can be reconstructed by resubmission.
        self._lineage_lock = threading.Lock()
        self._lineage_tasks: Dict[TaskID, Dict[str, Any]] = {}  # tid -> {spec, live}
        self._lineage_by_oid: Dict[ObjectID, TaskID] = {}
        self._recovery_inflight: Dict[TaskID, threading.Event] = {}
        # actor state
        self._actor_addr_cache: Dict[str, Tuple[Tuple[str, int], int]] = {}  # id -> (addr, version)
        self._actor_hub = _ActorStateHub(self)
        self._actor_dispatchers: Dict[str, _ActorDispatcher] = {}
        self._actor_disp_lock = threading.Lock()
        self._pending_actor_tasks: Dict[TaskID, Dict[str, Any]] = {}
        self._actor_task_contained: Dict[TaskID, List[ObjectID]] = {}
        # actors whose first round-trip (create → first task result) has
        # been stamped on the lifecycle timeline already
        self._actor_first_ping_seen: set = set()
        self._actor_pending_lock = debug_locks.maybe_wrap(
            threading.Lock(), "core_worker.CoreWorker._actor_pending_lock")

        # blocked-in-get tracking (CPU release protocol, see get())
        self._blocked_depth = 0
        self._blocked_lock = threading.Lock()

        # Borrow interest ledger. The owner keeps a borrower *set* (one
        # entry per borrower process, idempotent add); this process sends
        # RemoveBorrower exactly once — when its total interest in the oid
        # (deserialized claims + unclaimed handed-off borrows) hits zero.
        # oid -> {"owner": addr, "interest": int, "claimed": bool}
        self._borrow_state: Dict[ObjectID, Dict[str, Any]] = {}
        # owned put-objects whose payload contains nested refs (pinned)
        self._put_contained: Dict[ObjectID, List[ObjectID]] = {}
        # return-oid -> borrows a remote worker registered on OUR behalf
        # (handed-off borrows; interest released at outer-ref release —
        # advisor finding, round 1: unclaimed handoffs pinned forever)
        self._handoff_borrows: Dict[ObjectID, List[Tuple[ObjectID, Tuple[str, int]]]] = {}
        self._borrow_lock = debug_locks.maybe_wrap(
            threading.Lock(), "core_worker.CoreWorker._borrow_lock")
        from concurrent.futures import ThreadPoolExecutor as _TPE

        self._borrow_release_pool = _TPE(max_workers=1, thread_name_prefix="borrow-release")
        w = worker_mod.global_worker
        if w is not None:
            w.reference_counter.set_borrow_release_callback(self._on_borrow_released)

        self._shutdown = False
        # task-event buffer → GCS (reference: task_event_buffer.h feeding
        # GcsTaskManager; drives the state API's task listings)
        self._task_events: List[dict] = []
        self._task_events_lock = threading.Lock()
        threading.Thread(
            target=self._task_event_flush_loop, daemon=True,
            name="task-events",
        ).start()
        if is_driver and config.log_to_driver:
            threading.Thread(
                target=self._log_to_driver_loop, daemon=True,
                name="log-to-driver",
            ).start()
        # owner-side borrower liveness sweep (dead borrowers must not pin
        # objects forever; reference: WaitForRefRemoved)
        self._borrower_ping_failures: Dict[Tuple[str, int], int] = {}
        t = threading.Thread(
            target=self._borrower_liveness_loop, daemon=True,
            name="borrower-sweep",
        )
        t.start()

    def _handle_debug_dump(self, reason: str = "requested",
                           info: Optional[dict] = None) -> dict:
        """GCS-initiated flight-recorder dump (failure fan-out)."""
        path = obs_dump.dump_now(reason, extra=info)
        return {"ok": path is not None, "path": path}

    # ==================================================================
    # Task events (reference: task_event_buffer.h → GcsTaskManager)
    # ==================================================================
    def _record_task_event(self, task_id: TaskID, name: str, state: str,
                           kind: str = "task") -> None:
        ev = {
            "task_id": task_id.hex(),
            "name": name,
            "state": state,  # SUBMITTED | FINISHED | FAILED
            "kind": kind,  # task | actor_task
            "job_id": self.job_id.hex(),
            "worker": self.worker_id_hex[:16],
            "ts": time.time(),
        }
        with self._task_events_lock:
            self._task_events.append(ev)
            if len(self._task_events) > 10_000:
                del self._task_events[:5_000]
        if obs_tracing.active():
            # mirror lifecycle transitions onto the event bus so the
            # flight recorder shows them interleaved with spans
            obs_events.record_event("task_state", **ev)

    def _task_event_flush_loop(self) -> None:
        while not self._shutdown:
            time.sleep(1.0)
            with self._task_events_lock:
                batch, self._task_events = self._task_events, []
            if not batch:
                continue
            try:
                self.gcs.call_oneway("ReportTaskEvents", events=batch)
            except Exception:  # noqa: BLE001
                pass

    def _log_to_driver_loop(self) -> None:
        """Print worker log lines on the driver (reference:
        _private/log_monitor.py tailing worker logs to the driver)."""
        import sys

        # start at the CURRENT tail: a fresh driver must not replay the
        # cluster's whole historical log backlog
        seq = None
        while not self._shutdown:
            time.sleep(1.0)
            try:
                reply = self.gcs.call(
                    "GetLogs", after_seq=seq or 0, limit=0 if seq is None else 1000,
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                continue
            if seq is None:
                seq = reply.get("latest_seq", 0)
                continue
            for s, node_id, worker_id, line in reply.get("lines", []):
                seq = max(seq, s)
                print(f"({worker_id[:8]} {node_id[:8]}) {line}",
                      file=sys.stderr)

    # ==================================================================
    # Owner-side object services
    # ==================================================================
    def _handle_get_object(self, object_id_bin: bytes) -> dict:
        oid = ObjectID(object_id_bin)
        e = self.memory_store.get_if_exists(oid)
        if e is not None:
            kind = e.value[0]
            if kind == "inline":
                return {"status": "inline", "data": e.value[1]}
            return {"status": "plasma", "node_id": e.value[1]}
        # distinguish "not created yet" from "owner already freed it" so
        # borrowers get ObjectLostError instead of waiting forever
        if self._ref_counter().has_reference(oid):
            return {"status": "pending"}
        return {"status": "freed"}

    def _handle_get_objects_status(self, object_id_bins: List[bytes]) -> List[dict]:
        """Batched GetObject — one RPC covers every ref wait() is watching
        on this owner (replaces the per-ref polling the round-2 review
        flagged; reference: pubsub object-location channel)."""
        return [self._handle_get_object(b) for b in object_id_bins]

    def _handle_wait_object(self, object_id_bin: bytes, timeout_s: float = 10.0) -> dict:
        oid = ObjectID(object_id_bin)
        state = self._handle_get_object(object_id_bin)
        if state["status"] != "pending":
            return state
        f = self.memory_store.as_future(oid)
        try:
            f.result(timeout=timeout_s)
        except Exception:
            pass
        return self._handle_get_object(object_id_bin)

    def _handle_add_borrower(self, object_id_bin: bytes, borrower: Tuple[str, int]) -> dict:
        oid = ObjectID(object_id_bin)
        # add_borrower is atomic: it refuses to resurrect an entry for an
        # already-freed object (the borrower then gets status "freed")
        epoch = self._ref_counter().add_borrower(oid, tuple(borrower))
        if epoch is not None:
            return {"ok": True, "epoch": epoch}
        return {"ok": False, "freed": True}

    def _handle_remove_borrower(
        self, object_id_bin: bytes, borrower: Tuple[str, int], epoch: int = None
    ) -> dict:
        w = worker_mod.global_worker
        if w is not None:
            w.reference_counter.remove_borrower(
                ObjectID(object_id_bin), tuple(borrower), epoch=epoch
            )
        return {"ok": True}

    # -- borrower side (this process holds refs it does not own) --------
    #
    # Interest ledger: the owner keeps one registration per borrower
    # process; this process sends RemoveBorrower once, when its total
    # interest (claims + unclaimed handoffs) hits zero, carrying the
    # highest registration epoch it knows — the owner discards a Remove
    # older than its stored epoch, so a queued Remove racing a concurrent
    # re-borrow of the same oid cannot wipe the fresh registration.
    def on_ref_created(self, oid: ObjectID, owner_addr: Tuple[str, int]) -> None:
        """Called by ObjectRef.__init__ for refs carrying an owner address.
        First sighting of a borrowed oid → synchronously register with the
        owner (synchronous so the sender's pin is still alive — closing
        the free-before-borrow race). If a handed-off borrow already
        registered this process, only the claim is recorded locally."""
        if owner_addr == self.address or self._ref_counter().is_owned(oid):
            return
        with self._borrow_lock:
            st = self._borrow_state.get(oid)
            if st is None:
                st = {"owner": owner_addr, "interest": 0, "claimed": False,
                      "epoch": 0}
                self._borrow_state[oid] = st
                need_send = True
            else:
                if st["claimed"]:
                    return
                need_send = False
            st["claimed"] = True
            st["interest"] += 1

        if need_send:
            try:
                rep = get_client(owner_addr).call(
                    "AddBorrower", object_id_bin=oid.binary(),
                    borrower=self.address, timeout=10,
                )
                self._note_borrow_epoch(oid, (rep or {}).get("epoch"))
            except Exception:
                pass  # owner gone: get() will surface ObjectLostError

    def _note_borrow_epoch(self, oid: ObjectID, epoch) -> None:
        if epoch is None:
            return
        with self._borrow_lock:
            st = self._borrow_state.get(oid)
            if st is not None and epoch > st["epoch"]:
                st["epoch"] = epoch

    @staticmethod
    def _parse_borrow(entry) -> Tuple[ObjectID, Tuple[str, int], int]:
        # wire format: (oid_bin, owner_addr, epoch); epoch 0 = unknown
        b, addr, epoch = entry
        return ObjectID(b), tuple(addr), int(epoch or 0)

    def _record_handoff_borrows(self, outer: ObjectID, ret: dict) -> None:
        borrows = ret.get("borrows")
        if not borrows:
            return
        pairs = [self._parse_borrow(e) for e in borrows]
        with self._borrow_lock:
            for inner, owner, epoch in pairs:
                st = self._borrow_state.get(inner)
                if st is None:
                    self._borrow_state[inner] = {
                        "owner": owner, "interest": 1, "claimed": False,
                        "epoch": epoch,
                    }
                else:
                    st["interest"] += 1
                    if epoch > st["epoch"]:
                        st["epoch"] = epoch
            # Fire-and-forget ordering: the outer return ref can already be
            # released before the reply lands — then free_object has already
            # run and nothing will ever pop this entry. Release now.
            if self._ref_counter().has_reference(outer):
                self._handoff_borrows[outer] = pairs
                pairs = None
        if pairs:
            self._dec_borrow_interest([p[0] for p in pairs])

    def _release_unclaimed_handoffs(self, outer: ObjectID) -> None:
        """Outer return ref released: drop one interest unit per nested
        handed-off borrow (claims hold their own unit)."""
        with self._borrow_lock:
            pairs = self._handoff_borrows.pop(outer, None)
        if pairs:
            self._dec_borrow_interest([p[0] for p in pairs])

    def _absorb_dropped_handoffs(self, reply: dict) -> None:
        """A reply we will never hand to the user (late/failed/retried task)
        may still carry borrows an executing worker registered on our
        behalf; deregister any the ledger has no interest in."""
        dropped = list(reply.get("dropped_borrows") or [])
        for ret in reply.get("returns") or []:
            dropped.extend(ret.get("borrows") or [])
        if not dropped:
            return
        to_remove = []
        with self._borrow_lock:
            for entry in dropped:
                inner, owner, epoch = self._parse_borrow(entry)
                st = self._borrow_state.get(inner)
                if st is None:
                    to_remove.append((inner, owner, epoch))
                elif epoch > st["epoch"]:
                    st["epoch"] = epoch  # ledger covers it; track epoch
        self._queue_remove_borrowers(to_remove)

    def _dec_borrow_interest(self, oids: List[ObjectID]) -> None:
        to_remove = []
        with self._borrow_lock:
            for oid in oids:
                st = self._borrow_state.get(oid)
                if st is None:
                    continue
                st["interest"] -= 1
                if st["interest"] <= 0:
                    del self._borrow_state[oid]
                    to_remove.append((oid, st["owner"], st["epoch"]))
        self._queue_remove_borrowers(to_remove)

    def _queue_remove_borrowers(
        self, pairs: List[Tuple[ObjectID, Tuple[str, int], int]]
    ) -> None:
        if not pairs:
            return

        def _send():
            for inner, owner, epoch in pairs:
                with self._borrow_lock:
                    if inner in self._borrow_state:
                        continue  # re-borrowed since queued; still live
                try:
                    get_client(owner).call_oneway(
                        "RemoveBorrower", object_id_bin=inner.binary(),
                        borrower=self.address, epoch=epoch or None,
                    )
                except Exception:
                    pass

        self._borrow_release_pool.submit(_send)

    def _borrower_liveness_loop(self) -> None:
        period = max(1.0, config.borrower_liveness_period_s)
        while not self._shutdown:
            time.sleep(period)
            try:
                self._borrower_liveness_sweep()
            except Exception:
                pass

    def _borrower_liveness_sweep(self) -> None:
        # remove_borrower is irreversible, and a live-but-busy borrower
        # (GIL held by a multi-GB deserialize, host pause) can miss pings:
        # require 3 consecutive failures with generous timeouts (~90s of
        # silence at the default 30s period) before declaring it dead.
        # Pings run CONCURRENTLY — a serial sweep is O(borrowers × 10s
        # timeout) on one thread (round-2 review finding).
        from concurrent.futures import ThreadPoolExecutor

        rc = self._ref_counter()
        by_addr = rc.borrower_addrs()
        for addr in list(self._borrower_ping_failures):
            if addr not in by_addr:
                self._borrower_ping_failures.pop(addr, None)
        if not by_addr:
            return

        def ping(addr):
            try:
                get_client(addr).call("Ping", timeout=10)
                return addr, True
            except Exception:  # noqa: BLE001
                return addr, False

        with ThreadPoolExecutor(max_workers=min(16, len(by_addr))) as pool:
            results = list(pool.map(ping, by_addr))
        for addr, alive in results:
            if alive:
                self._borrower_ping_failures.pop(addr, None)
                continue
            n = self._borrower_ping_failures.get(addr, 0) + 1
            self._borrower_ping_failures[addr] = n
            if n >= 3:
                self._borrower_ping_failures.pop(addr, None)
                for oid in by_addr[addr]:
                    rc.remove_borrower(oid, addr)

    def _on_borrow_released(self, oid: ObjectID) -> None:
        """Last local ObjectRef for a borrowed oid died → drop the claim's
        interest unit. The RemoveBorrower (if interest hits zero) goes out
        on the pool thread: this is called from ObjectRef.__del__ paths
        where a dead owner's connect timeout must not stall the releaser."""
        with self._borrow_lock:
            st = self._borrow_state.get(oid)
            if st is None or not st["claimed"]:
                return
            st["claimed"] = False
        self._dec_borrow_interest([oid])

    # ==================================================================
    # Objects
    # ==================================================================
    def _ref_counter(self):
        w = worker_mod.global_worker
        if w is None:  # interpreter/driver shutdown race: no-op counter
            from ray_tpu._private.reference_counter import ReferenceCounter

            return ReferenceCounter()
        return w.reference_counter

    def put(self, value: Any) -> ObjectRef:
        w = worker_mod.global_worker
        oid = ObjectID.from_index(w.current_task_id, w.next_put_index())
        from ray_tpu._private.serialization import (
            collect_object_refs,
            serialize_prepare,
        )

        with collect_object_refs() as col:
            sv = serialize_prepare(value)
        try:
            self.put_prepared(oid, sv)
        finally:
            sv.release()
        rc = self._ref_counter()
        rc.add_owned_object(oid)
        if col.refs:
            # pin refs nested inside the stored value for the outer
            # object's lifetime; released when the outer object is freed
            inner = [r.id() for r in col.refs]
            for i in inner:
                rc.add_submitted_task_ref(i)
            with self._borrow_lock:
                self._put_contained[oid] = inner
        return ObjectRef(oid, owner_addr=self.address)

    def put_prepared(self, oid: ObjectID, sv) -> None:
        """Store a prepared (two-phase) serialized value as an owned
        object: inline in the memory store below the threshold, else
        written in place into the reserved shm mapping
        (Create → write-in-place → Seal — 0 intermediate payload
        copies). The caller releases ``sv``."""
        if obs_tracing.active():
            obs_events.record_event(
                "object_put", size=sv.total, job_id=self.job_id.hex(),
                inline=sv.total <= config.object_store_inline_max_bytes)
        if sv.total <= config.object_store_inline_max_bytes:
            # small objects stay inline in the owner memory store; the
            # join is expected here and counted on the "inline" series,
            # keeping the zero-copy "put" invariant series clean
            self.memory_store.put(
                oid, ("inline", sv.to_bytes(copy_path="inline")))
        else:
            self._plasma_put_segments(oid, sv)
            self.memory_store.put(oid, ("plasma", self.node_id))

    def put_serialized(self, oid: ObjectID, data: bytes) -> None:
        if obs_tracing.active():
            obs_events.record_event(
                "object_put", size=len(data), job_id=self.job_id.hex(),
                inline=len(data) <= config.object_store_inline_max_bytes)
        if len(data) <= config.object_store_inline_max_bytes:
            self.memory_store.put(oid, ("inline", data))
        else:
            self._plasma_put_with_backpressure(oid, data)
            self.memory_store.put(oid, ("plasma", self.node_id))

    def _plasma_create_backpressure(self, oid: ObjectID, size: int):
        """Create in the local store; on FULL ask the raylet to spill and
        retry (reference: plasma/create_request_queue.h backpressure —
        ours is client-retry over raylet-driven disk spilling)."""
        if size > self.plasma.pool_size:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity "
                f"{self.plasma.pool_size}"
            )
        deadline = time.monotonic() + 60.0
        while True:
            try:
                return self.plasma.create(oid, size)
            except ObjectStoreFullError:
                # hard bound even while spills keep freeing (concurrent
                # producers can otherwise livelock this loop)
                if time.monotonic() > deadline:
                    raise
                freed = 0
                try:
                    reply = self.raylet.call(
                        "SpillObjects", needed_bytes=size, timeout=120
                    )
                    freed = reply.get("freed", 0)
                except Exception:  # noqa: BLE001
                    pass
                if not freed:
                    time.sleep(config.object_store_full_delay_ms / 1000.0)

    def _plasma_put_with_backpressure(self, oid: ObjectID, data: bytes) -> None:
        """Write a serialized object into the local store, spilling on
        pressure; no-op if the object already exists."""
        try:
            buf = self._plasma_create_backpressure(oid, len(data))
        except FileExistsError:
            return
        try:
            buf.data[:] = data
            buf.seal()
        except BaseException:
            buf.abort()
            raise

    def _plasma_put_segments(self, oid: ObjectID, sv) -> None:
        """Zero-copy plasma put: reserve ``sv.total`` bytes, write the
        serialized frame in place (payload moves source → shm exactly
        once), seal. No-op if the object already exists."""
        try:
            buf = self._plasma_create_backpressure(oid, sv.total)
        except FileExistsError:
            return
        try:
            sv.write_into(buf.data)
            buf.seal()
        except BaseException:
            buf.abort()
            raise

    def _node_raylet_addr(self, node_id: str) -> Optional[Tuple[str, int]]:
        with self._node_addrs_lock:
            addr = self._node_addrs.get(node_id)
        if addr is not None:
            return addr
        try:
            infos = self.gcs.call_retrying("GetAllNodeInfo")
        except Exception:  # noqa: BLE001
            return None
        with self._node_addrs_lock:
            for n in infos:
                self._node_addrs[n["NodeID"]] = (n["NodeManagerAddress"], n["NodeManagerPort"])
            return self._node_addrs.get(node_id)

    def _lookup_moved_object(self, oid: ObjectID,
                             not_node: str) -> Optional[str]:
        """A drained node pushed its primary copies to a survivor and
        registered them with the GCS — consult that directory before
        declaring the object lost."""
        try:
            rep = self.gcs.call_retrying(
                "LookupObjectLocations", object_id_bins=[oid.binary()],
                timeout=10)
        except Exception:  # noqa: BLE001
            return None
        new_node = (rep or {}).get(oid.binary())
        return new_node if new_node and new_node != not_node else None

    def _pull_remote_object(self, oid: ObjectID, node_id: str,
                            _retry: bool = True,
                            _check_moved: bool = True) -> None:
        """Fetch a plasma object from another node's store into the local
        store, chunked (reference: object_manager.cc:221 Pull + :614
        ReceiveObjectChunk; ours is reader-driven over the raylet RPC).
        When the recorded node is gone (drained/preempted), falls back to
        the GCS moved-object directory before giving up."""
        if _check_moved:
            try:
                return self._pull_remote_object(
                    oid, node_id, _retry=_retry, _check_moved=False)
            except ObjectLostError:
                new_node = self._lookup_moved_object(oid, node_id)
                if new_node is None:
                    raise
                logger.info(
                    "object %s moved off drained node %s -> %s",
                    oid.hex()[:12], node_id[:12], new_node[:12])
                self._pull_remote_object(
                    oid, new_node, _retry=_retry, _check_moved=False)
                if self._ref_counter().is_owned(oid):
                    # later reads go straight to the new primary. OWNED
                    # entries only: writing a location entry into a
                    # BORROWER's store would shadow its owner-mediated
                    # path (_get_one asks the owner, who can reconstruct
                    # from lineage) with a dead end once this copy and
                    # the directory entry are gone
                    self.memory_store.put(oid, ("plasma", new_node))
                return
        addr = self._node_raylet_addr(node_id)
        if addr is None:
            raise ObjectLostError(
                f"object {oid.hex()} lives on unknown node {node_id[:12]}"
            )
        chunk_len = config.object_pull_chunk_bytes
        client = get_client(addr)

        def _chunk(offset: int) -> dict:
            try:
                rep = client.call(
                    "PullObjectChunk", object_id_bin=oid.binary(), offset=offset,
                    length=chunk_len, timeout=60,
                )
            except (RpcConnectionError, ConnectionError, OSError, TimeoutError) as e:
                raise ObjectLostError(
                    f"object {oid.hex()} unreachable: node {node_id[:12]} is down ({e})"
                ) from None
            if rep.get("status") != "ok":
                raise ObjectLostError(
                    f"object {oid.hex()} is gone from node {node_id[:12]}"
                )
            return rep

        first = _chunk(0)
        total = first["total"]
        try:
            buf = self._plasma_create_backpressure(oid, total)
        except FileExistsError:
            # another thread's pull is in flight: wait for its seal WITHOUT
            # a long blocking store get (the store client is one shared
            # locked connection — a parked get would block the puller's
            # seal() and deadlock until timeout)
            deadline = time.monotonic() + config.rpc_call_timeout_s
            while time.monotonic() < deadline:
                state = self.plasma.contains_state(oid)
                if state == 0:
                    return  # sealed
                if state == 2:
                    break  # the other pull aborted — take over
                time.sleep(0.005)
            if _retry:
                return self._pull_remote_object(oid, node_id, _retry=False)
            raise ObjectLostError(
                f"object {oid.hex()}: concurrent local pull never sealed"
            )
        ok = False
        try:
            data = first["data"]
            buf.data[: len(data)] = data
            off = len(data)
            while off < total:
                rep = _chunk(off)
                d = rep["data"]
                buf.data[off : off + len(d)] = d
                off += len(d)
            buf.seal()
            ok = True
        finally:
            if not ok:
                buf.abort()

    def _deserialize_entry(self, oid: ObjectID, entry_value: tuple) -> Any:
        kind = entry_value[0]
        if kind == "inline":
            if obs_tracing.active():
                obs_events.record_event(
                    "object_get", size=len(entry_value[1]),
                    job_id=self.job_id.hex(), inline=True)
            val = deserialize(entry_value[1])
        else:  # plasma
            node_id = entry_value[1]
            if node_id != self.node_id and not self.plasma.contains(oid):
                self._pull_remote_object(oid, node_id)
            elif node_id == self.node_id and not self.plasma.contains(oid):
                # maybe spilled to local disk — restore with backpressure:
                # a "full" store (pinned live values) may free up as the
                # user's arrays are collected
                deadline = time.monotonic() + 60.0
                while True:
                    try:
                        st = self.raylet.call(
                            "RestoreObject", object_id_bin=oid.binary(), timeout=120
                        ).get("status")
                    except Exception:  # noqa: BLE001
                        st = "absent"
                    if st != "full" or time.monotonic() > deadline:
                        break
                    time.sleep(config.object_store_full_delay_ms / 1000.0)
            [view] = self.plasma.get([oid], timeout_ms=int(config.rpc_call_timeout_s * 1000))
            if view is None:
                raise ObjectLostError(f"object {oid.hex()} not in local store")
            if obs_tracing.active():
                obs_events.record_event(
                    "object_get", size=len(view),
                    job_id=self.job_id.hex(), inline=False)
            # the get-pin lives exactly as long as the deserialized value:
            # released when the last zero-copy array viewing the region is
            # collected (so long-lived refs don't wedge the store full)
            val = deserialize(
                view, release_cb=functools.partial(self._safe_plasma_release, oid)
            )
        if isinstance(val, RayTaskError):
            raise val.as_instanceof_cause()
        if isinstance(val, BaseException):
            raise val
        return val

    def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        oid = ref.id()
        while True:
            e = self.memory_store.get_if_exists(oid)
            if e is not None:
                try:
                    return self._deserialize_entry(oid, e.value)
                except ObjectLostError:
                    # owned object whose plasma primary is gone: reconstruct
                    # from lineage (object_recovery_manager.h:41)
                    if self._try_recover_object(oid):
                        continue
                    raise
            # do we own it (pending task) or borrow it?
            owned = self._ref_counter().is_owned(oid)
            if owned:
                timeout = None if deadline is None else max(0.0, deadline - time.monotonic())
                f = self.memory_store.as_future(oid)
                try:
                    f.result(timeout=timeout)
                except concurrent.futures.TimeoutError:
                    # 3.10: futures.TimeoutError is NOT the builtin — a
                    # bare `except TimeoutError` let the raw futures
                    # timeout escape get() instead of GetTimeoutError
                    raise GetTimeoutError(f"Get timed out for {oid.hex()}")
                except TimeoutError:
                    raise GetTimeoutError(f"Get timed out for {oid.hex()}")
                continue
            # borrowed: check local plasma first (e.g. same-node producer)
            if self.plasma.contains(oid):
                return self._deserialize_entry(oid, ("plasma", self.node_id))
            owner = ref.owner_address
            if owner is None:
                # last resort: blocking plasma wait
                [view] = self.plasma.get([oid], timeout_ms=1000)
                if view is not None:
                    self.plasma.release(oid)
                    return self._deserialize_entry(oid, ("plasma", self.node_id))
                if deadline is not None and time.monotonic() > deadline:
                    raise GetTimeoutError(f"Get timed out for {oid.hex()} (no owner known)")
                continue
            client = get_client(tuple(owner))
            wait_s = 10.0 if deadline is None else min(10.0, max(0.1, deadline - time.monotonic()))
            try:
                reply = client.call("WaitObject", object_id_bin=oid.binary(), timeout_s=wait_s)
            except (RpcConnectionError, ConnectionError, OSError) as e2:
                raise ObjectLostError(
                    f"owner of {oid.hex()} at {owner} is unreachable: {e2}"
                ) from None
            if reply["status"] == "inline":
                val = deserialize(reply["data"])
                if isinstance(val, RayTaskError):
                    raise val.as_instanceof_cause()
                if isinstance(val, BaseException):
                    raise val
                return val
            if reply["status"] == "plasma":
                try:
                    return self._deserialize_entry(oid, ("plasma", reply["node_id"]))
                except ObjectLostError:
                    # borrowed object lost: ask the OWNER to reconstruct it
                    # (owners hold the lineage; this chains through nested
                    # dependencies because each recovery re-runs the task)
                    try:
                        rep2 = client.call(
                            "RecoverObject", object_id_bin=oid.binary(),
                            timeout_s=60.0, timeout=75,
                        )
                    except (RpcConnectionError, ConnectionError, OSError, TimeoutError) as e3:
                        raise ObjectLostError(
                            f"object {oid.hex()} lost and its owner at {owner} "
                            f"could not recover it: {e3}"
                        ) from None
                    st = rep2.get("status")
                    if st == "inline":
                        val = deserialize(rep2["data"])
                        if isinstance(val, RayTaskError):
                            raise val.as_instanceof_cause() from None
                        if isinstance(val, BaseException):
                            raise val
                        return val
                    if st == "plasma" and self._object_reachable(oid, rep2["node_id"]):
                        return self._deserialize_entry(oid, ("plasma", rep2["node_id"]))
                    raise
            if reply["status"] == "freed":
                raise ObjectLostError(
                    f"object {oid.hex()} was already freed by its owner "
                    "(all references released before this read)"
                )
            if deadline is not None and time.monotonic() > deadline:
                raise GetTimeoutError(f"Get timed out for {oid.hex()}")

    def _maybe_notify_blocked(self, refs: Sequence[ObjectRef]) -> bool:
        """Executor workers blocked in get() hand their CPU back to the
        raylet so dependent tasks can run (reference: NotifyDirectCallTask
        Blocked/Unblocked — avoids nested-task deadlock)."""
        if self.is_driver:
            return False
        w = worker_mod.global_worker
        lease_id = getattr(w, "current_lease_id", None)
        if lease_id is None:
            return False
        if all(
            self.memory_store.contains(r.id()) or self.plasma.contains(r.id()) for r in refs
        ):
            return False
        with self._blocked_lock:
            self._blocked_depth += 1
            first = self._blocked_depth == 1
        if first:
            try:
                self.raylet.call("NotifyWorkerBlocked", lease_id=lease_id, timeout=5)
            except Exception:
                pass
        return True

    def _notify_unblocked(self) -> None:
        w = worker_mod.global_worker
        lease_id = getattr(w, "current_lease_id", None)
        with self._blocked_lock:
            self._blocked_depth -= 1
            last = self._blocked_depth == 0
        if last and lease_id:
            try:
                self.raylet.call("NotifyWorkerUnblocked", lease_id=lease_id, timeout=5)
            except Exception:
                pass

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        notified = self._maybe_notify_blocked(refs)
        try:
            return [self._get_one(r, deadline) for r in refs]
        finally:
            if notified:
                self._notify_unblocked()

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while True:
            still: List[ObjectRef] = []
            by_owner: Dict[Tuple[str, int], List[ObjectRef]] = {}
            for r in pending:
                if self.memory_store.contains(r.id()) or self.plasma.contains(r.id()):
                    ready.append(r)
                elif not self._ref_counter().is_owned(r.id()) and r.owner_address:
                    by_owner.setdefault(tuple(r.owner_address), []).append(r)
                else:
                    still.append(r)
            # one batched status RPC per owner per round (not per ref)
            for owner, owner_refs in by_owner.items():
                try:
                    replies = get_client(owner).call(
                        "GetObjectsStatus",
                        object_id_bins=[r.id().binary() for r in owner_refs],
                        timeout=5,
                    )
                    for r, reply in zip(owner_refs, replies):
                        (ready if reply["status"] != "pending" else still).append(r)
                except Exception:  # noqa: BLE001
                    still.extend(owner_refs)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        ready = ready[:num_returns]
        ready_ids = {r.id() for r in ready}
        not_ready = [r for r in refs if r.id() not in ready_ids]
        return ready, not_ready

    def as_future(self, ref: ObjectRef) -> Future:
        out: Future = Future()

        def _bg():
            try:
                out.set_result(self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                out.set_exception(e)

        threading.Thread(target=_bg, daemon=True).start()
        return out

    def free_object(self, oid: ObjectID) -> None:
        # A refcount can hit zero from a coroutine on the io loop (e.g.
        # _fail_actor_task in a dispatcher); the release path may block
        # (plasma socket, GCS node lookup on a cold cache) — run it on
        # the release pool so the loop never waits on itself.
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None:
            self._borrow_release_pool.submit(self._free_object_sync, oid)
            return
        self._free_object_sync(oid)

    def _free_object_sync(self, oid: ObjectID) -> None:
        with self._borrow_lock:
            inner = self._put_contained.pop(oid, None)
        if inner:
            self._release_contained_refs(inner)
        self._release_unclaimed_handoffs(oid)
        self._evict_lineage(oid)
        e = self.memory_store.get_if_exists(oid)
        self.memory_store.delete(oid)
        if e is not None and e.value[0] == "plasma":
            # get-pins belong to live deserialized values, not the ref; the
            # store defers the delete until outstanding pins drop
            self._delete_plasma_copy(oid, e.value[1])

    def _safe_plasma_release(self, oid: ObjectID) -> None:
        """Release a store get-pin; called from GC when the last value
        viewing the object's memory dies (may run on any thread, possibly
        during interpreter shutdown)."""
        if self._shutdown:
            return
        try:
            self.plasma.release(oid)
        except Exception:  # noqa: BLE001
            pass

    def _delete_plasma_copy(self, oid: ObjectID, home_node: str) -> None:
        """Best-effort delete of a plasma object: local replica + the
        primary copy on its home node."""
        try:
            self.plasma.delete(oid)
        except Exception:
            pass
        if home_node != self.node_id:
            addr = self._node_raylet_addr(home_node)
            if addr is not None:
                try:
                    get_client(addr).call_oneway(
                        "DeleteObject", object_id_bin=oid.binary()
                    )
                except Exception:
                    pass

    # ==================================================================
    # Task submission (reference: normal_task_submitter.cc SubmitTask /
    # OnWorkerIdle / RequestNewWorkerIfNeeded)
    # ==================================================================
    def _serialize_args(
        self, args: tuple, kwargs: dict
    ) -> Tuple[List[TaskArg], Dict[str, TaskArg], List[ObjectID]]:
        """Returns (args, kwargs, contained_oids). Both direct ref args and
        refs NESTED inside pickled values are pinned (submitted-task refs,
        reference_counter.h:44) until the task completes; contained_oids
        lists the nested ones so completion can unpin them."""
        out_args: List[TaskArg] = []
        contained: List[ObjectID] = []

        def conv(v) -> TaskArg:
            if isinstance(v, ObjectRef):
                self._ref_counter().add_submitted_task_ref(v.id())
                owner = v.owner_address or self.address
                return TaskArg(is_ref=True, object_id=v.id(), owner_addr=tuple(owner))
            from ray_tpu._private.serialization import (
                collect_object_refs,
                serialize_prepare,
            )

            with collect_object_refs() as col:
                sv = serialize_prepare(v)
            try:
                for r in col.refs:
                    self._ref_counter().add_submitted_task_ref(r.id())
                    contained.append(r.id())
                if sv.total > config.object_store_inline_max_bytes:
                    # promote big arg to an owned shared-memory object,
                    # written in place (zero-copy)
                    w = worker_mod.global_worker
                    oid = ObjectID.from_index(
                        w.current_task_id, w.next_put_index())
                    self.put_prepared(oid, sv)
                    self._ref_counter().add_owned_object(oid)
                    self._ref_counter().add_submitted_task_ref(oid)
                    return TaskArg(
                        is_ref=True, object_id=oid, owner_addr=self.address)
                return TaskArg(
                    is_ref=False, value=sv.to_bytes(copy_path="inline"))
            finally:
                sv.release()

        for a in args:
            out_args.append(conv(a))
        kw = {k: conv(v) for k, v in kwargs.items()}
        return out_args, kw, contained

    def _release_contained_refs(self, oids: List[ObjectID]) -> None:
        rc = self._ref_counter()
        for oid in oids:
            rc.remove_submitted_task_ref(oid)

    def _release_task_refs(self, spec: TaskSpec) -> None:
        """Release every pin a normal-task submission took (direct ref
        args + nested refs). Idempotent — completion and the several
        failure paths may both reach it."""
        if getattr(spec, "_refs_released", False):
            return
        spec._refs_released = True  # type: ignore[attr-defined]
        for a in spec.args + list(getattr(spec, "kwargs_map", {}).values()):
            if a.is_ref and a.object_id is not None:
                self._ref_counter().remove_submitted_task_ref(a.object_id)
        self._release_contained_refs(getattr(spec, "contained_refs", []))

    def submit_task(self, remote_function, args, kwargs, opts: TaskOptions):
        w = worker_mod.global_worker
        task_id = TaskID.for_normal_task(self.job_id)
        streaming = opts.num_returns == "streaming"
        ser_args, ser_kwargs, contained = self._serialize_args(args, kwargs)
        from ray_tpu._private.serialization import dumps_function

        # pickle the function ONCE per RemoteFunction (reference exports
        # once to the GCS function table); per-submit cloudpickle was the
        # dominant driver-side cost for small tasks. The key is the
        # content hash of the BYTES (not the source): closures from one
        # factory share source but not cell values.
        fn_bytes = getattr(remote_function, "_pickled_function", None)
        if fn_bytes is None:
            import hashlib

            fn_bytes = dumps_function(remote_function._function)
            remote_function._pickled_function = fn_bytes
            remote_function._pickled_fn_key = hashlib.sha1(
                fn_bytes).hexdigest()

        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.NORMAL_TASK,
            function_descriptor=remote_function._descriptor,
            args=ser_args,
            num_returns=0 if streaming else opts.num_returns,
            resources=opts.resources,
            scheduling_strategy=opts.scheduling_strategy,
            # a partially-consumed stream cannot be transparently replayed
            max_retries=0 if streaming else opts.max_retries,
            retry_exceptions=opts.retry_exceptions,
            caller_addr=self.address,
            serialized_function=fn_bytes,
            function_key=remote_function._pickled_fn_key,
            # prepared HERE on the user thread: packaging uploads block on
            # GCS RPCs, which must never run on the io loop (_pack_spec
            # executes there during the push)
            runtime_env=self._prepared_runtime_env(opts.runtime_env),
        )
        spec.is_streaming_generator = streaming
        spec.kwargs_map = ser_kwargs  # type: ignore[attr-defined]
        spec.contained_refs = contained  # type: ignore[attr-defined]
        # trace propagation: the caller's active sampled span (or None —
        # one thread-local read when tracing is idle) rides the spec so
        # the executor's span parents here across the process boundary
        spec.trace_ctx = obs_tracing.for_outbound()  # type: ignore[attr-defined]
        spec.submit_ts = time.time()  # type: ignore[attr-defined]
        return_ids = spec.return_ids()
        for oid in return_ids:
            self._ref_counter().add_owned_object(oid, pending_creation=True)
        self._pending_tasks[task_id] = {"spec": spec, "retries_left": spec.max_retries}
        self._record_task_event(task_id, spec.function_descriptor.repr_name, "SUBMITTED")
        obs_timeline.mark_task(task_id.hex(), "submit",
                               job_id=self.job_id.hex())
        gen = self._register_stream(task_id) if streaming else None
        self.loop_thread.call_soon(self._submit_spec_threadsafe, spec)
        if streaming:
            return gen
        return [ObjectRef(oid, owner_addr=self.address) for oid in return_ids]

    def _submit_spec_threadsafe(self, spec: TaskSpec) -> None:
        import asyncio

        asyncio.ensure_future(self._submit_spec(spec))

    async def _submit_spec(self, spec: TaskSpec) -> None:
        """Runs on the io loop: acquire a lease (cached or new) and push."""
        sc = spec.scheduling_class
        with self._lock:
            lease = None
            for entry in self._leases.get(sc, []):
                if not entry.busy:
                    entry.busy = True
                    lease = entry
                    break
        if lease is None:
            from collections import deque

            self._task_queue.setdefault(sc, deque()).append(spec)
            await self._maybe_request_lease(sc, spec)
            return
        await self._push_tasks([spec], lease)

    # -- scheduling strategies (reference: scheduling policies under
    # src/ray/raylet/scheduling/policy/ — node-affinity, spread, labels;
    # hybrid top-k lives in the raylet's spillback picker) -------------
    async def _node_view(self, force: bool = False) -> List[dict]:
        """Alive nodes from the GCS, cached briefly (lease requests are
        off the task hot path, but SPREAD shouldn't hammer the GCS).
        Raises _TransientSchedulingError when the GCS is unreachable and
        no cache exists — a control-plane blip must not read as 'node
        dead' to a hard affinity/label constraint."""
        now = time.monotonic()
        cached = self._node_view_cache
        if not force and cached and now - cached[0] < 2.0:
            return cached[1]
        try:
            infos = await self.gcs.acall("GetAllNodeInfo", timeout=10)
        except Exception as e:  # noqa: BLE001
            if cached:
                return cached[1]
            raise _TransientSchedulingError(str(e)) from None
        # DRAINING nodes are alive but must not receive new placements —
        # schedulers route around them the moment the drain is published
        alive = [n for n in infos
                 if n.get("Alive") and not n.get("Draining")]
        self._node_view_cache = (now, alive)
        return alive

    async def _lease_target(
        self, strategy, resources: Dict[str, float],
    ) -> Tuple[Tuple[str, int], bool, str]:
        """(raylet addr to lease from, allow_spillback, hard_kind) per
        strategy. hard_kind is "" (no hard constraint), "pinned" (hard
        NodeAffinity — infeasible at that node means infeasible, full
        stop) or "labeled" (hard NodeLabel — another matching or future
        autoscaled node may still fit, so the raylet queues rather than
        fails when autoscaling is on)."""
        import random as _random

        kind = strategy.kind
        if kind == "NODE_AFFINITY":
            for force in (False, True):
                for n in await self._node_view(force=force):
                    if n["NodeID"] == strategy.node_id:
                        return ((n["NodeManagerAddress"],
                                 n["NodeManagerPort"]), bool(strategy.soft),
                                "" if strategy.soft else "pinned")
                # the cache can be up to 2s stale — a just-registered
                # node must not read as dead for a HARD constraint, so
                # re-check against a fresh view before failing
            if strategy.soft:
                return self.raylet_addr, True, ""
            raise _InfeasibleStrategyError(
                f"node {strategy.node_id!r} is not alive "
                f"(NodeAffinity soft=False)")
        if kind == "SPREAD":
            try:
                nodes = await self._node_view()
            except _TransientSchedulingError:
                return self.raylet_addr, True, ""  # preference, not constraint
            if nodes:
                self._spread_rr += 1
                n = nodes[self._spread_rr % len(nodes)]
                return ((n["NodeManagerAddress"],
                         n["NodeManagerPort"]), True, "")
        if kind == "NODE_LABEL":
            hard = strategy.node_labels or {}

            def _matching(view):
                return [n for n in view
                        if all(n.get("Labels", {}).get(k) == v
                               for k, v in hard.items())]

            def _fitting(nodes):
                # among matching nodes, only those whose TOTALS fit the
                # request can ever serve it — picking an undersized match
                # would read as infeasible at that node even though a
                # bigger match exists
                return [m for m in nodes
                        if all(m.get("Resources", {}).get(k, 0.0) >= v
                               for k, v in resources.items())]

            matches = _matching(await self._node_view())
            if not matches or not _fitting(matches):
                # stale-cache re-check before committing to failure or an
                # undersized match: a just-registered fitting node must
                # not be missed for a HARD constraint
                matches = _matching(await self._node_view(force=True))
            if matches:
                pool = _fitting(matches) or matches
                # prefer nodes with spare CPU, pick randomly among them
                # (a deterministic 'best' pick herds every concurrent
                # submitter onto one matching node for the cache window)
                free = [m for m in pool if m.get(
                    "AvailableResources", {}).get("CPU", 0.0) > 0]
                n = _random.choice(free or pool)
                # soft label preference: matching node first, but any
                # node is legal — spillback allowed, no hard constraint
                return ((n["NodeManagerAddress"],
                         n["NodeManagerPort"]),
                        bool(strategy.soft),
                        "" if strategy.soft else "labeled")
            if strategy.soft:
                return self.raylet_addr, True, ""
            raise _InfeasibleStrategyError(
                f"no alive node matches labels {hard!r} "
                f"(NodeLabel soft=False)")
        return self.raylet_addr, True, ""

    async def _maybe_request_lease(self, sc, spec: TaskSpec) -> None:
        with self._lock:
            inflight = self._lease_requests_inflight.get(sc, 0)
            queued = len(self._task_queue.get(sc, []))
            if inflight >= min(queued, config.max_pending_lease_requests_per_class):
                return
            self._lease_requests_inflight[sc] = inflight + 1
        try:
            strategy = spec.scheduling_strategy
            kwargs = dict(
                resources=spec.resources,
                scheduling_class=sc,
                job_id=self.job_id.hex(),
                pg_id=strategy.placement_group_id,
                bundle_index=strategy.placement_group_bundle_index,
                lease_timeout=config.worker_lease_timeout_ms / 1000.0,
                timeout=config.worker_lease_timeout_ms / 1000.0 + 10.0,
                runtime_env_hash=spec.runtime_env_hash(),
            )
            try:
                target_addr, allow_spill, hard_kind = \
                    await self._lease_target(strategy, spec.resources)
            except _InfeasibleStrategyError as e:
                err = RayTaskError(
                    spec.function_descriptor.repr_name, str(e))
                self._fail_queued_tasks(sc, err)
                return
            except _TransientSchedulingError as e:
                # GCS blip with a cold node-view cache: the not-granted
                # path below re-kicks the request — the constraint might
                # be perfectly satisfiable
                raise RuntimeError(f"node view unavailable: {e}") from None
            kwargs["allow_spillback"] = allow_spill
            # "pinned"/"labeled" tells the raylet it must run the lease
            # locally or fail/queue precisely, never redirect it to a
            # node that may violate the constraint
            kwargs["hard_node_constraint"] = hard_kind
            client = self.raylet if tuple(target_addr) == tuple(
                self.raylet_addr) else get_client(tuple(target_addr))
            granted_by: Tuple[str, int] = tuple(target_addr)
            reply = await client.acall("RequestWorkerLease", **kwargs)
            if reply.get("spillback"):
                # local raylet redirected us to a node with capacity
                # (reference: normal_task_submitter.cc:413 re-request at the
                # spillback node); a spilled request cannot spill again
                granted_by = tuple(reply["spillback"])
                reply = await get_client(granted_by).acall(
                    "RequestWorkerLease",
                    **dict(kwargs, allow_spillback=False),
                )
        except Exception as e:  # noqa: BLE001
            if not self._shutdown:
                logger.warning("lease request failed: %s", e)
            reply = {"granted": False, "error": str(e)}
        finally:
            with self._lock:
                self._lease_requests_inflight[sc] = self._lease_requests_inflight.get(sc, 1) - 1
        if not reply.get("granted"):
            if reply.get("infeasible"):
                err = RayTaskError(
                    spec.function_descriptor.repr_name,
                    f"Infeasible resource request: {reply.get('error')}",
                )
                self._fail_queued_tasks(sc, err)
            else:
                # re-kick if tasks remain
                with self._lock:
                    remaining = bool(self._task_queue.get(sc)) and not self._shutdown
                if remaining:
                    import asyncio

                    await asyncio.sleep(0.1)
                    # fresh task, not a nested await: a long outage would
                    # otherwise grow an unbounded coroutine await chain
                    asyncio.ensure_future(
                        self._maybe_request_lease(sc, spec))
            return
        entry = _LeaseEntry(reply["lease_id"], tuple(reply["worker_addr"]), granted_by)
        obs_timeline.mark_task(spec.task_id.hex(), "lease",
                               job_id=self.job_id.hex())
        logger.debug("lease %s granted (worker %s)", entry.lease_id[:8], entry.worker_addr)
        with self._lock:
            self._leases.setdefault(sc, []).append(entry)
        await self._on_lease_idle(sc, entry)

    def _fail_queued_tasks(self, sc, err: Exception) -> None:
        with self._lock:
            specs = self._task_queue.pop(sc, [])
        data = serialize(err if isinstance(err, RayTaskError) else RayTaskError("task", str(err)))
        for s in specs:
            if s.is_streaming_generator:
                self._fail_stream(s.task_id, err)
            for oid in s.return_ids():
                self.memory_store.put(oid, ("inline", data))
            self._release_task_refs(s)
            with self._lock:  # vs _claim_push_completion (executor)
                self._pending_tasks.pop(s.task_id, None)

    @staticmethod
    def _batchable(spec: TaskSpec) -> bool:
        """A spec may share a PushTaskBatch only if it carries NO
        ObjectRef arguments. Batch replies arrive all-at-once, so a task
        whose arg references a sibling earlier in the SAME batch would
        block in the worker fetching a value whose reply is still
        waiting behind the batch — a deadlock until timeout. Ref-arg
        tasks go solo; queue FIFO then guarantees their dependencies
        were pushed in an earlier roundtrip."""
        if spec.is_streaming_generator:
            return False  # delivers out-of-band; keep the RPC solo
        if getattr(spec, "contained_refs", None):
            return False  # refs nested inside arg structures
        for a in spec.args:
            if a.is_ref:
                return False
        for a in getattr(spec, "kwargs_map", {}).values():
            if a.is_ref:
                return False
        return True

    async def _on_lease_idle(self, sc, entry: _LeaseEntry) -> None:
        """Reuse the leased worker for queued tasks, or return it. Pops
        a batch of batchable specs for one PushTaskBatch roundtrip —
        with a deep queue the per-task RPC roundtrip (not execution)
        dominates small-task throughput. The batch size adapts to the
        class's parallelism: popping 32 tasks onto one worker while 7
        other leases sit idle would serialize work the old path ran in
        parallel, so a shallow queue splits across the known workers."""
        specs: List[TaskSpec] = []
        with self._lock:
            queue = self._task_queue.get(sc)
            if queue:
                n_workers = (len(self._leases.get(sc, []))
                             + self._lease_requests_inflight.get(sc, 0))
                cap = min(max(1, config.task_push_batch_size),
                          max(1, len(queue) // max(1, n_workers)))
                while queue and len(specs) < cap:
                    if specs and not self._batchable(queue[0]):
                        break  # non-batchable spec starts its own push
                    s = queue.popleft()
                    specs.append(s)
                    if not self._batchable(s):
                        break
                entry.busy = True
        if not specs:
            # Keep the granted lease WARM instead of returning it: the
            # next same-class submit then pushes straight to the leased
            # worker — one worker RPC, no raylet/GCS touch (reference:
            # normal_task_submitter.cc keeps leased workers for reuse;
            # ours previously paid RequestWorkerLease + SetLeaseContext
            # + ReturnWorkerLease around EVERY sync small task). The
            # sweeper returns it after worker_lease_keepalive_s idle so
            # held CPU cannot starve other classes for long.
            if config.worker_lease_keepalive_s <= 0:
                await self._return_lease(sc, entry)
                return
            entry.busy = False
            entry.last_used = time.monotonic()
            self._ensure_lease_sweeper()
            return
        await self._push_tasks(specs, entry)

    def _ensure_lease_sweeper(self) -> None:
        """io-loop only."""
        if self._lease_sweeper is None or self._lease_sweeper.done():
            self._lease_sweeper = asyncio.ensure_future(
                self._lease_sweeper_loop())

    async def _lease_sweeper_loop(self) -> None:
        """Return idle kept-alive leases to their raylets. Lives while any
        lease exists; re-armed by the next idle lease after it exits."""
        while not self._shutdown:
            keep = max(0.05, config.worker_lease_keepalive_s)
            await asyncio.sleep(keep / 2)
            now = time.monotonic()
            expired: List[Tuple[Any, _LeaseEntry]] = []
            with self._lock:
                for sc, entries in list(self._leases.items()):
                    if self._task_queue.get(sc):
                        continue  # queued work will claim these
                    for e in list(entries):
                        if not e.busy and now - e.last_used > keep:
                            entries.remove(e)
                            expired.append((sc, e))
                    if not entries:
                        self._leases.pop(sc, None)
                alive = any(self._leases.values())
            for _sc, e in expired:
                try:
                    await self._lease_raylet(e).acall(
                        "ReturnWorkerLease", lease_id=e.lease_id)
                except Exception as exc:  # noqa: BLE001
                    if not self._shutdown:
                        logger.debug("keepalive lease return %s failed: %s",
                                     e.lease_id[:8], exc)
            if not alive:
                # re-check under the lock: a lease that went idle while
                # the returns above were in flight would otherwise never
                # be swept (_ensure_lease_sweeper saw us still running),
                # pinning its worker for the driver's lifetime
                with self._lock:
                    alive = any(self._leases.values())
                if not alive:
                    return

    async def _return_lease(self, sc, entry: _LeaseEntry) -> None:
        with self._lock:
            entries = self._leases.get(sc, [])
            if entry in entries:
                entries.remove(entry)
        try:
            await self._lease_raylet(entry).acall("ReturnWorkerLease", lease_id=entry.lease_id)
        except Exception as e:  # noqa: BLE001
            if not self._shutdown:
                logger.warning("ReturnWorkerLease %s failed: %s", entry.lease_id[:8], e)

    def _lease_raylet(self, entry: _LeaseEntry) -> RpcClient:
        if entry.raylet_addr is None or tuple(entry.raylet_addr) == tuple(self.raylet_addr):
            return self.raylet
        return get_client(tuple(entry.raylet_addr))

    async def _push_tasks(self, specs: List[TaskSpec],
                          entry: _LeaseEntry,
                          drain_final: bool = False) -> None:
        sc = specs[0].scheduling_class
        live: List[TaskSpec] = []
        for spec in specs:
            st = self._pending_tasks.get(spec.task_id)
            if st is not None:
                st["entry"] = entry  # cancel() needs the executing worker
                # Check AFTER assigning entry: a cancel() that ran earlier
                # (or concurrently — it sets cancelled before reading
                # entry) is seen here, so either we skip dispatch or
                # cancel() sends the CancelTask RPC; the race has no lost
                # interleaving.
                if st.get("cancelled"):
                    # don't dispatch; returns already poisoned
                    self._release_task_refs(spec)
                    with self._lock:  # vs _claim_push_completion
                        self._pending_tasks.pop(spec.task_id, None)
                    continue
            live.append(spec)
        if not live:
            entry.busy = False
            await self._on_lease_idle(sc, entry)
            return
        client = get_client(entry.worker_addr)
        shipped = self._fns_shipped.setdefault(tuple(entry.worker_addr),
                                               set())
        payloads = []
        in_batch: set = set()
        for spec in live:
            p = self._pack_spec(spec)
            if drain_final:
                # override: the draining worker must accept this push —
                # no other node can host the task (see
                # _handle_lease_recalled)
                p["drain_final"] = True
            if spec.function_key and (spec.function_key in shipped
                                      or spec.function_key in in_batch):
                # bytes already live in that worker's key cache — or an
                # earlier member of THIS batch carries them (the worker
                # executes in order and caches before reaching us) —
                # ship the hash only (the worker answers need_function
                # on a cache miss and we resend with bytes below)
                p["serialized_function"] = None
            elif spec.function_key:
                in_batch.add(spec.function_key)
            payloads.append(p)
        try:
            if len(payloads) == 1:
                replies = [await client.acall(
                    "PushTask", spec_payload=payloads[0],
                    timeout=-1,  # tasks can run arbitrarily long
                )]
            else:
                batch_reply = await client.acall(
                    "PushTaskBatch", spec_payloads=payloads, timeout=-1)
                if batch_reply.get("node_draining"):
                    await self._handle_lease_recalled(live, entry)
                    return
                replies = batch_reply["replies"]
        except RemoteError as e:
            # worker is alive but the push itself failed (e.g. payload
            # could not be decoded) — a task error, NOT a worker death
            err_by_name = {}
            for spec in live:
                st = self._pending_tasks.get(spec.task_id)
                if st is None or st.get("completed_attempt") == spec.attempt_number:
                    continue  # completed via NormalTaskDone before the raise
                name = spec.function_descriptor.repr_name
                if name not in err_by_name:
                    err_by_name[name] = serialize(
                        RayTaskError(name, str(e)))
                data = err_by_name[name]
                for oid in spec.return_ids():
                    self.memory_store.put(oid, ("inline", data))
                self._release_task_refs(spec)
                with self._lock:  # vs _claim_push_completion
                    self._pending_tasks.pop(spec.task_id, None)
            entry.busy = False
            await self._on_lease_idle(sc, entry)
            return
        except Exception as e:  # noqa: BLE001
            logger.warning("push of %d task(s) failed: %s", len(live), e)
            await self._handle_worker_failure(
                live, entry, e,
                lease_was_warm=entry.warm and isinstance(
                    e, (RpcConnectionError, ConnectionError, OSError)))
            return
        batched = len(payloads) > 1
        recalled = [spec for spec, reply in zip(live, replies)
                    if reply.get("node_draining")]
        if recalled:
            # the worker refused mid-stream: its node started draining.
            # Complete what did run, then re-lease the rest elsewhere.
            done_pairs = [(s, r) for s, r in zip(live, replies)
                          if not r.get("node_draining")]
            for spec, reply in done_pairs:
                if reply.get("need_function"):
                    recalled.append(spec)  # resubmit ships the bytes
                    continue
                if spec.function_key:
                    shipped.add(spec.function_key)
                if not batched or self._claim_push_completion(
                        spec.task_id, spec.attempt_number):
                    self._complete_task(spec, reply)
            await self._handle_lease_recalled(recalled, entry)
            return
        retry_with_bytes: List[TaskSpec] = []
        for spec, reply in zip(live, replies):
            if reply.get("need_function"):
                shipped.discard(spec.function_key)
                retry_with_bytes.append(spec)
                continue
            if spec.function_key:
                shipped.add(spec.function_key)
            if batched:
                # batch members were (probably) already completed by the
                # worker's out-of-band NormalTaskDone push — this reply
                # is the fallback for a lost push; claim exactly once
                if self._claim_push_completion(spec.task_id,
                                               spec.attempt_number):
                    self._complete_task(spec, reply)
            else:
                self._complete_task(spec, reply)
        for pos, spec in enumerate(retry_with_bytes):
            # worker evicted the function from its key cache: one more
            # roundtrip with the bytes attached
            try:
                retry_payload = self._pack_spec(spec)
                if drain_final:
                    retry_payload["drain_final"] = True
                reply = await client.acall(
                    "PushTask", spec_payload=retry_payload,
                    timeout=-1)
            except Exception as e:  # noqa: BLE001
                # EVERY not-yet-pushed retry spec fails/retries with
                # this one — dropping them would leave their returns
                # unresolved forever
                await self._handle_worker_failure(
                    retry_with_bytes[pos:], entry, e)
                return
            if spec.function_key:
                shipped.add(spec.function_key)
            self._complete_task(spec, reply)
        entry.busy = False
        entry.last_used = time.monotonic()
        entry.warm = True  # survived a full push: see _LeaseEntry.warm
        if drain_final:
            # the node is draining: the finished batch was its last work
            # from this lease — retire it rather than pool it for reuse
            with self._lock:
                entries = self._leases.get(sc, [])
                if entry in entries:
                    entries.remove(entry)
            try:
                await self._lease_raylet(entry).acall(
                    "ReturnWorkerLease", lease_id=entry.lease_id)
            except Exception:  # noqa: BLE001 — raylet may already be gone
                pass
            return
        await self._on_lease_idle(sc, entry)

    def _driver_py_paths(self) -> List[str]:
        """sys.path entries to replicate on workers so cloudpickle
        by-reference functions resolve (reference: runtime_env py_modules /
        working_dir shipping, _private/runtime_env/working_dir.py)."""
        import os
        import sys

        cached = getattr(self, "_py_paths_cache", None)
        if cached is None:
            cached = [p for p in sys.path if p and os.path.isdir(p)]
            self._py_paths_cache = cached
        return cached

    def _prepared_runtime_env(self, task_env) -> dict:
        """Merge job-level + per-task runtime envs and package local dirs
        into the GCS KV (reference: runtime_env plugins upload through
        the agent; _private/runtime_env/working_dir.py)."""
        from ray_tpu._private import runtime_env as rt

        job_env = getattr(self, "job_runtime_env", None)
        if not job_env and not task_env:
            return {}
        merged = rt.merge_runtime_envs(job_env, task_env)
        return rt.prepare_runtime_env(merged, self.gcs)

    def _pack_spec(self, spec: TaskSpec) -> dict:
        return {
            "py_paths": self._driver_py_paths(),
            "runtime_env": spec.runtime_env,  # prepared at submit time
            "streaming": spec.is_streaming_generator,
            "task_id": spec.task_id.binary(),
            "job_id": spec.job_id.binary(),
            "task_type": spec.task_type.value,
            "function_name": spec.function_descriptor.repr_name,
            "serialized_function": spec.serialized_function,
            "function_key": spec.function_key,
            "args": [
                {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for a in spec.args
            ],
            "kwargs": {
                k: {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for k, a in getattr(spec, "kwargs_map", {}).items()
            },
            "num_returns": spec.num_returns,
            "caller_addr": spec.caller_addr,
            "retry_exceptions": spec.retry_exceptions,
            "attempt_number": spec.attempt_number,
            "trace_ctx": getattr(spec, "trace_ctx", None),
            "submit_ts": getattr(spec, "submit_ts", 0.0),
        }

    def _claim_push_completion(self, task_id: TaskID,
                               attempt_number: int) -> bool:
        """Exactly-once gate between a batch task's out-of-band
        NormalTaskDone push and the fallback reply in the PushTaskBatch
        return: whichever arrives first completes the task, the other
        is dropped. Keyed by attempt so a stale push from a pre-retry
        attempt cannot complete the retried one."""
        with self._lock:
            st = self._pending_tasks.get(task_id)
            if st is None:
                return False  # completed-and-popped, or cancelled+reaped
            if st["spec"].attempt_number != attempt_number:
                return False
            if st.get("completed_attempt") == attempt_number:
                return False
            st["completed_attempt"] = attempt_number
            return True

    def _handle_normal_task_done(self, task_id_bin: bytes,
                                 attempt_number: int, reply: dict) -> dict:
        """A leased worker finished one member of a PushTaskBatch —
        deliver its result now, not when the whole batch returns (a
        fast task must be visible to ray.wait while a slow batch
        sibling still runs)."""
        task_id = TaskID(bytes(task_id_bin))
        with self._lock:
            st = self._pending_tasks.get(task_id)
            spec = st["spec"] if st is not None else None
        if spec is None:
            return {"ok": False}
        if not self._claim_push_completion(task_id, attempt_number):
            return {"ok": False}
        self._complete_task(spec, reply)
        return {"ok": True}

    # a recalled batch gets this many drain-final pushes back to its
    # (still alive, draining) worker before we give up and take the
    # re-lease path anyway — a backstop against a worker that keeps
    # refusing even the override
    _DRAIN_FINAL_MAX_PUSHES = 3

    async def _drain_alternative_exists(self, spec: TaskSpec) -> bool:
        """Can any alive, non-draining node host `spec` at all? Checked
        against node TOTALS on a forced-fresh view: re-leasing a
        recalled task is only correct if somewhere else can ever run
        it."""
        resources = spec.resources or {}
        if not resources:
            return True  # any node hosts a plain task
        try:
            nodes = await self._node_view(force=True)
        except _TransientSchedulingError:
            return False  # blind: keep the work on the live lease
        return any(
            all(n.get("Resources", {}).get(k, 0.0) >= v
                for k, v in resources.items())
            for n in nodes)

    async def _handle_lease_recalled(self, specs: List[TaskSpec],
                                     entry: _LeaseEntry) -> None:
        """The leased worker's node is draining and refused the push
        (nothing executed): return the lease to its raylet and re-lease
        the tasks elsewhere — a recall is the lease layer's problem, so
        it never charges the tasks' max_retries.

        Re-leasing is only correct when some other node can actually
        host the task. A task pinned to the draining node by a custom
        resource would re-lease into an infeasible request and FAIL —
        even though the drain deadline exists precisely so in-flight
        work can finish. These tasks were leased before the drain
        started, so they ARE in-flight: push them back to the original
        worker with a `drain_final` override (which the draining worker
        honors) and retire the lease when the batch completes."""
        sc = specs[0].scheduling_class
        if not await self._drain_alternative_exists(specs[0]):
            pushes = entry.drain_final_pushes + 1
            if pushes <= self._DRAIN_FINAL_MAX_PUSHES:
                entry.drain_final_pushes = pushes
                logger.info(
                    "lease %s recalled (node draining) but no other "
                    "node fits the resource spec; finishing %d task(s) "
                    "on the draining node", entry.lease_id[:8], len(specs))
                await self._push_tasks(specs, entry, drain_final=True)
                return
        with self._lock:
            entries = self._leases.get(sc, [])
            if entry in entries:
                entries.remove(entry)
        try:
            await self._lease_raylet(entry).acall(
                "ReturnWorkerLease", lease_id=entry.lease_id)
        except Exception:  # noqa: BLE001 — the raylet may already be gone
            pass
        logger.info("lease %s recalled (node draining); re-leasing %d "
                    "task(s)", entry.lease_id[:8], len(specs))
        for spec in specs:
            st = self._pending_tasks.get(spec.task_id)
            if st is None or st.get("cancelled"):
                continue
            spec.attempt_number += 1
            await self._submit_spec(spec)

    # a task gets this many FREE re-leases after warm-lease connection
    # failures before the failure starts charging max_retries — bounds a
    # pathological churn loop without ever failing a task merely because
    # the keepalive cache handed it a dead worker. Known tradeoff: the
    # caller cannot tell "worker died between pushes" (pure cache fault)
    # from "worker died mid-push" — a max_retries=0 task whose worker is
    # killed WHILE executing gets re-run once here. The reference makes
    # the same call at its lease layer; tasks needing strict
    # at-most-once must be idempotent or use actors.
    _WARM_FREE_RETRIES = 3

    async def _handle_worker_failure(self, specs: List[TaskSpec],
                                     entry: _LeaseEntry,
                                     error: Exception,
                                     lease_was_warm: bool = False) -> None:
        sc = specs[0].scheduling_class
        with self._lock:
            entries = self._leases.get(sc, [])
            if entry in entries:
                entries.remove(entry)
        try:
            await self._lease_raylet(entry).acall(
                "ReturnWorkerLease", lease_id=entry.lease_id, worker_dead=True
            )
        except Exception:
            pass
        # the worker is gone: its function cache went with it
        self._fns_shipped.pop(tuple(entry.worker_addr), None)
        for spec in specs:
            st = self._pending_tasks.get(spec.task_id)
            if st is None or st.get("completed_attempt") == spec.attempt_number:
                # this batch member already completed through its
                # out-of-band NormalTaskDone push before the worker (or
                # the connection) died — failing it now would overwrite
                # a delivered result with WorkerCrashedError
                continue
            free = False
            if lease_was_warm and st is not None and not st.get("cancelled"):
                # a warm (keepalive-cached) lease whose worker vanished
                # (SIGKILL between calls, node drained): the failure is
                # the CACHE's, not the task's — re-lease elsewhere
                # without touching retries_left, even at max_retries=0
                warm_used = getattr(spec, "_warm_free_retries", 0)
                if warm_used < self._WARM_FREE_RETRIES:
                    spec._warm_free_retries = warm_used + 1  # type: ignore[attr-defined]
                    free = True
            if st is not None and not st.get("cancelled") and \
                    (free or st["retries_left"] > 0):
                if not free:
                    st["retries_left"] -= 1
                spec.attempt_number += 1
                logger.info("retrying task %s (%s)", spec.task_id.hex()[:12],
                            "free: warm lease lost its worker" if free
                            else f"{st['retries_left']} left")
                await self._submit_spec(spec)
            else:
                err = RayTaskError(
                    spec.function_descriptor.repr_name,
                    f"Worker died while running the task: {error}",
                    WorkerCrashedError(str(error)),
                )
                if spec.is_streaming_generator:
                    self._fail_stream(spec.task_id, err.as_instanceof_cause())
                data = serialize(err)
                for oid in spec.return_ids():
                    self.memory_store.put(oid, ("inline", data))
                self._release_task_refs(spec)
                with self._lock:  # vs _claim_push_completion
                    st0 = self._pending_tasks.pop(spec.task_id, None)
                if not (st0 or {}).get("cancelled"):
                    self._record_task_event(
                        spec.task_id, spec.function_descriptor.repr_name, "FAILED")

    def _complete_task(self, spec: TaskSpec, reply: dict) -> None:
        if spec.is_streaming_generator:
            # yields were delivered out-of-band; finalize idempotently in
            # case the worker's StreamingDone push was lost
            self._handle_streaming_done(
                spec.task_id.binary(),
                count=reply.get("streaming_done", 0),
                error=reply.get("stream_error"),
            )
            self._release_task_refs(spec)
            with self._lock:  # vs _claim_push_completion (executor)
                st0 = self._pending_tasks.pop(spec.task_id, None)
            if not (st0 or {}).get("cancelled"):  # cancel() already logged
                self._record_task_event(
                    spec.task_id, spec.function_descriptor.repr_name,
                    "FAILED" if reply.get("stream_error") else "FINISHED")
            return
        returns = reply.get("returns", [])
        retriable_error = reply.get("retriable_error")
        st_pre = self._pending_tasks.get(spec.task_id)
        if st_pre is not None and st_pre.get("cancelled"):
            # the CancelTask raced with completion and lost: keep the
            # TaskCancelledError poison in the return objects, discard the
            # late reply (and its plasma copies, or they leak)
            self._absorb_dropped_handoffs({"returns": returns})
            if reply.get("dropped_borrows"):
                self._absorb_dropped_handoffs(
                    {"dropped_borrows": reply["dropped_borrows"]})
            for i, ret in enumerate(returns):
                if ret.get("kind") != "inline":
                    oid = ObjectID.from_index(spec.task_id, i + 1)
                    self._delete_plasma_copy(
                        oid, ret.get("node_id", self.node_id))
            self._release_task_refs(spec)
            with self._lock:  # vs _claim_push_completion (executor)
                self._pending_tasks.pop(spec.task_id, None)
            return
        if reply.get("dropped_borrows"):
            # borrows registered for values that failed to package — the
            # error reply supersedes them (advisor/review finding, round 2)
            self._absorb_dropped_handoffs({"dropped_borrows": reply["dropped_borrows"]})
        if retriable_error and spec.retry_exceptions:
            st = self._pending_tasks.get(spec.task_id)
            if st is not None and st["retries_left"] > 0 and not st.get("cancelled"):
                st["retries_left"] -= 1
                spec.attempt_number += 1
                self._absorb_dropped_handoffs({"returns": returns})
                self.loop_thread.call_soon(self._submit_spec_threadsafe, spec)
                return
        plasma_returns: List[ObjectID] = []
        for i, ret in enumerate(returns):
            oid = ObjectID.from_index(spec.task_id, i + 1)
            self._record_handoff_borrows(oid, ret)
            node = ret.get("node_id", self.node_id)
            if not self._ref_counter().has_reference(oid):
                # already freed (user dropped the ref mid-flight, or a
                # recovery re-ran a task with some returns out of scope):
                # don't resurrect the entry — and drop the plasma copy the
                # executor just wrote, or it leaks forever
                if ret["kind"] != "inline":
                    self._delete_plasma_copy(oid, node)
                continue
            if ret["kind"] == "inline":
                self.memory_store.put(oid, ("inline", ret["data"]))
            else:
                self.memory_store.put(oid, ("plasma", node))
                plasma_returns.append(oid)
        if plasma_returns:
            # pin lineage: keep the spec (and thereby its arg-ref pins) so
            # these shared-memory returns can be reconstructed if their
            # node dies (task_manager.h:195); released when the last return
            # goes out of scope (free_object)
            with self._lineage_lock:
                ent = self._lineage_tasks.get(spec.task_id)
                if ent is None:
                    self._lineage_tasks[spec.task_id] = {
                        "spec": spec,
                        "live": set(plasma_returns),
                    }
                    for oid in plasma_returns:
                        self._lineage_by_oid[oid] = spec.task_id
            # close the has_reference/registration race: a ref dropped in
            # the window would have found no lineage to evict — re-check now
            # that the entry is visible
            for oid in plasma_returns:
                if not self._ref_counter().has_reference(oid):
                    self._evict_lineage(oid)
        else:
            self._release_task_refs(spec)
        with self._lock:  # vs _claim_push_completion (executor)
            st0 = self._pending_tasks.pop(spec.task_id, None)
        if not (st0 or {}).get("cancelled"):  # cancel() already logged
            # the worker sets retriable_error on ANY application exception;
            # if it survives to here the retries are exhausted -> FAILED
            self._record_task_event(
                spec.task_id, spec.function_descriptor.repr_name,
                "FAILED" if retriable_error else "FINISHED")
            obs_timeline.mark_task(spec.task_id.hex(), "result",
                                   job_id=self.job_id.hex())
            submit_ts = getattr(spec, "submit_ts", 0.0)
            if submit_ts:
                _task_latency_histogram().observe(
                    max(0.0, time.time() - submit_ts),
                    tags={"kind": "task"})

    # ==================================================================
    # Object recovery (reference: object_recovery_manager.h:41 — the owner
    # resubmits the creating task when a plasma primary is lost)
    # ==================================================================
    def _evict_lineage(self, oid: ObjectID) -> None:
        """Return object went out of scope: drop it from its task's lineage;
        release the task's arg pins when no returns remain in scope."""
        with self._lineage_lock:
            tid = self._lineage_by_oid.pop(oid, None)
            if tid is None:
                return
            ent = self._lineage_tasks.get(tid)
            if ent is None:
                return
            ent["live"].discard(oid)
            spec = ent["spec"] if not ent["live"] else None
            if spec is not None:
                del self._lineage_tasks[tid]
        if spec is not None:
            self._release_task_refs(spec)

    def _try_recover_object(self, oid: ObjectID, wait_s: float = 0.5) -> bool:
        """Resubmit the task that created a lost object. Returns True if a
        recovery was started (or was already in flight) — the caller should
        re-wait on the memory store."""
        with self._lineage_lock:
            tid = self._lineage_by_oid.get(oid)
            ent = self._lineage_tasks.get(tid) if tid is not None else None
            if ent is None:
                return False
            ev = self._recovery_inflight.get(tid)
            if ev is not None:
                leader = False
            else:
                leader = True
                ev = self._recovery_inflight[tid] = threading.Event()
                spec = ent["spec"]
                live = set(ent["live"])
        if not leader:
            ev.wait(timeout=30)
            time.sleep(wait_s)  # let the resubmission register
            return True
        try:
            attempts = getattr(spec, "_recovery_attempts", 0)
            if attempts >= 3:
                logger.error(
                    "object %s unrecoverable: task %s already reconstructed %d times",
                    oid.hex()[:12], spec.task_id.hex()[:12], attempts,
                )
                return False
            spec._recovery_attempts = attempts + 1  # type: ignore[attr-defined]
            logger.warning(
                "reconstructing object %s by resubmitting task %s (attempt %d)",
                oid.hex()[:12], spec.task_id.hex()[:12], attempts + 1,
            )
            # clear the stale locations so getters park on the re-creation
            for roid in spec.return_ids():
                if roid in live:
                    self.memory_store.delete(roid)
            spec.attempt_number += 1
            self._pending_tasks[spec.task_id] = {
                "spec": spec,
                "retries_left": spec.max_retries,
            }
            self.loop_thread.call_soon(self._submit_spec_threadsafe, spec)
            return True
        finally:
            ev.set()
            with self._lineage_lock:
                self._recovery_inflight.pop(tid, None)

    def _handle_recover_object(self, object_id_bin: bytes, timeout_s: float = 60.0) -> dict:
        """Borrower-triggered recovery: a worker holding a ref to OUR lost
        object asks us (the owner) to reconstruct it; replies with the new
        location once the resubmitted task lands. This is what makes chained
        reconstruction work — each lost dependency walks back to its owner."""
        oid = ObjectID(object_id_bin)
        state = self._handle_get_object(object_id_bin)
        if state["status"] == "plasma":
            if self._object_reachable(oid, state["node_id"]):
                return state  # healthy — the borrower's failure was transient
            if not self._try_recover_object(oid):
                return state
        elif state["status"] != "pending":
            return state
        f = self.memory_store.as_future(oid)
        try:
            f.result(timeout=timeout_s)
        except Exception:  # noqa: BLE001
            pass
        return self._handle_get_object(object_id_bin)

    def _object_reachable(self, oid: ObjectID, node_id: str) -> bool:
        if node_id == self.node_id:
            return self.plasma.contains(oid)
        addr = self._node_raylet_addr(node_id)
        if addr is None:
            return False
        try:
            rep = get_client(addr).call(
                "ContainsObject", object_id_bin=oid.binary(), timeout=10
            )
            return bool(rep.get("contains"))
        except Exception:  # noqa: BLE001
            return False

    # ==================================================================
    # Actors (reference: actor_task_submitter.cc; GCS-mediated creation
    # gcs_actor_manager.cc:314/:433)
    # ==================================================================
    def create_actor(self, actor_class, args, kwargs, opts: ActorOptions) -> ActorID:
        actor_id = ActorID.of(self.job_id)
        obs_timeline.mark_actor(actor_id.hex(), "submit",
                                job_id=self.job_id.hex())
        # contained/direct arg refs stay pinned for the actor's lifetime:
        # restarts replay __init__ from the same spec (gcs_actor_manager.cc:1721)
        ser_args, ser_kwargs, _ = self._serialize_args(args, kwargs)
        from ray_tpu._private.serialization import dumps_function

        spec_payload = {
            "py_paths": self._driver_py_paths(),
            "runtime_env": self._prepared_runtime_env(opts.runtime_env),
            "serialized_class": dumps_function(actor_class._cls),
            "class_name": actor_class._name,
            "args": [
                {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for a in ser_args
            ],
            "kwargs": {
                k: {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for k, a in ser_kwargs.items()
            },
            "max_concurrency": opts.max_concurrency,
            "max_restarts": opts.max_restarts,
        }
        import pickle

        from ray_tpu._private.runtime_env import env_hash

        actor_env_hash = env_hash(spec_payload["runtime_env"]) \
            if spec_payload["runtime_env"] else ""
        strategy = opts.scheduling_strategy
        reply = self.gcs.call_retrying(
            "RegisterActor",
            actor_id=actor_id.hex(),
            job_id=self.job_id.hex(),
            serialized_spec=pickle.dumps(spec_payload, protocol=5),
            name=opts.name,
            namespace=opts.namespace or "default",
            max_restarts=opts.max_restarts,
            resources=opts.resources,
            owner_addr=self.address,
            detached=(opts.lifetime == "detached"),
            get_if_exists=opts.get_if_exists,
            pg_id=strategy.placement_group_id,
            bundle_index=strategy.placement_group_bundle_index,
            cpu_scheduling_only=opts.cpu_scheduling_only,
            runtime_env_hash=actor_env_hash,
            scheduling_kind=strategy.kind,
            affinity_node_id=strategy.node_id,
            strategy_soft=strategy.soft,
            node_labels=strategy.node_labels,
        )
        if "error" in reply:
            raise ValueError(reply["error"])
        return ActorID.from_hex(reply["actor_id"])

    async def _resolve_actor_async(
        self, actor_id_hex: str, wait_alive_s: Optional[float] = None,
    ) -> Tuple[str, int]:
        """Resolve an actor's worker address via the GCS long-poll,
        awaited on the io loop (blocking gcs.call there would deadlock
        the loop against its own replies). 180s default: actor __init__
        may legitimately cold-import jax and build a model inside a
        fresh worker process; raise ``actor_wait_alive_timeout_s`` for
        thousand-actor bursts where the tail actor's creation backlog
        exceeds it."""
        if wait_alive_s is None:
            wait_alive_s = config.actor_wait_alive_timeout_s
        deadline = time.monotonic() + wait_alive_s
        cached = self._actor_addr_cache.get(actor_id_hex)
        if cached is not None:
            return cached[0]
        # change-driven, not polled: the shared hub wakes this waiter on
        # the actor's state transitions — a 2,000-actor creation burst
        # costs one Subscribe stream + one GetActorInfo per transition,
        # not 2,000 outstanding WaitActorUpdate polls
        ev = self._actor_hub.watch(actor_id_hex)
        try:
            while time.monotonic() < deadline:
                # warm path: the hub's freshest pushed event already
                # carries state + address — resolve from it with NO
                # GetActorInfo round-trip (the 2,000-actor burst then
                # costs one GCS query per actor, not one per wake)
                info = self._actor_hub.last_event.get(actor_id_hex)
                if not (info and (
                        (info.get("state") == "ALIVE"
                         and info.get("worker_addr"))
                        or info.get("state") == "DEAD")):
                    try:
                        info = await self.gcs.acall(
                            "GetActorInfo", actor_id=actor_id_hex,
                            timeout=15)
                    except (RpcConnectionError, ConnectionError, OSError,
                            TimeoutError):
                        await asyncio.sleep(0.5)
                        continue
                if info is None:
                    raise ActorDiedError(
                        f"Actor {actor_id_hex[:12]} does not exist")
                if info["state"] == "ALIVE" and info["worker_addr"]:
                    addr = tuple(info["worker_addr"])
                    self._actor_addr_cache[actor_id_hex] = (
                        addr, info["version"])
                    return addr
                if info["state"] == "DEAD":
                    raise ActorDiedError(
                        f"Actor {actor_id_hex[:12]} is dead: "
                        f"{info.get('death_cause', '')}")
                try:
                    await asyncio.wait_for(
                        ev.wait(),
                        timeout=min(10.0, max(
                            0.01, deadline - time.monotonic())))
                except asyncio.TimeoutError:
                    pass  # re-check against the deadline regardless
                ev.clear()
        finally:
            self._actor_hub.unwatch(actor_id_hex, ev)
        raise ActorUnavailableError(
            f"Actor {actor_id_hex[:12]} not schedulable in time")

    def submit_actor_task(self, handle, method_name, args, kwargs, opts: TaskOptions):
        actor_id: ActorID = handle._actor_id
        aid = actor_id.hex()
        task_id = TaskID.for_actor_task(actor_id)
        streaming = opts.num_returns == "streaming"
        n_returns = 0 if streaming else opts.num_returns
        return_ids = [ObjectID.from_index(task_id, i + 1) for i in range(n_returns)]
        for oid in return_ids:
            self._ref_counter().add_owned_object(oid, pending_creation=True)
        ser_args, ser_kwargs, contained = self._serialize_args(args, kwargs)
        # every pin taken for this task (direct ref args + promoted big
        # args + nested refs) — released exactly once on done/fail
        pinned = list(contained)
        for a in list(ser_args) + list(ser_kwargs.values()):
            if a.is_ref and a.object_id is not None:
                pinned.append(a.object_id)
        if pinned:
            with self._actor_pending_lock:
                self._actor_task_contained[task_id] = pinned
        payload = {
            "actor_id": aid,
            "task_id": task_id.binary(),
            "method_name": method_name,
            "caller_id": self.worker_id_hex,
            "num_returns": n_returns,
            "streaming": streaming,
            "args": [
                {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for a in ser_args
            ],
            "kwargs": {
                k: {
                    "is_ref": a.is_ref,
                    "value": a.value,
                    "object_id": a.object_id.binary() if a.object_id else None,
                    "owner_addr": a.owner_addr,
                }
                for k, a in ser_kwargs.items()
            },
            "caller_addr": self.address,
            "trace_ctx": obs_tracing.for_outbound(),
            "submit_ts": time.time(),
        }
        gen = self._register_stream(task_id) if streaming else None
        self._record_task_event(task_id, method_name, "SUBMITTED", kind="actor_task")
        self._get_dispatcher(aid).submit(payload, return_ids)
        if streaming:
            return gen
        return [ObjectRef(oid, owner_addr=self.address) for oid in return_ids]

    def _get_dispatcher(self, aid: str) -> _ActorDispatcher:
        with self._actor_disp_lock:
            disp = self._actor_dispatchers.get(aid)
            if disp is None or not disp.alive:
                disp = _ActorDispatcher(self, aid)
                self._actor_dispatchers[aid] = disp
            return disp

    def _handle_actor_tasks_done(self, results: List[dict]) -> dict:
        """Batched execution results pushed back by the actor's worker
        (one RPC per delivery batch instead of one per task)."""
        return {"ok": [self._handle_actor_task_done(**r).get("ok")
                       for r in results]}

    def _handle_actor_task_done(
        self, task_id_bin: bytes, returns: List[dict], dropped_borrows: list = None,
        streaming_done: Optional[int] = None, stream_error: Optional[bytes] = None,
        failed: bool = False,
    ) -> dict:
        """Execution result pushed back by the actor's worker."""
        tid = TaskID(task_id_bin)
        if dropped_borrows:
            self._absorb_dropped_handoffs({"dropped_borrows": dropped_borrows})
        if streaming_done is not None:
            # reliable finalizer for actor streaming methods (the direct
            # StreamingDone push may have been lost); idempotent
            self._handle_streaming_done(task_id_bin, streaming_done, stream_error)
        with self._actor_pending_lock:
            info = self._pending_actor_tasks.pop(tid, None)
            contained = self._actor_task_contained.pop(tid, [])
        self._release_contained_refs(contained)
        if info is None:
            # already failed (restart) — drop the late result, but the
            # executing worker still registered us as borrower of any refs
            # nested in it; deregister them or the owners pin forever
            self._absorb_dropped_handoffs({"returns": returns})
            return {"ok": False}
        for i, ret in enumerate(returns):
            oid = info["return_oids"][i]
            self._record_handoff_borrows(oid, ret)
            if ret["kind"] == "inline":
                self.memory_store.put(oid, ("inline", ret["data"]))
            else:
                self.memory_store.put(oid, ("plasma", ret.get("node_id", self.node_id)))
        self._record_task_event(
            tid, info.get("method", "actor_task"),
            "FAILED" if failed else "FINISHED", kind="actor_task")
        aid = info.get("aid")
        if aid and aid not in self._actor_first_ping_seen \
                and obs_timeline.enabled():
            self._actor_first_ping_seen.add(aid)
            obs_timeline.mark_actor(aid, "first_ping",
                                    job_id=self.job_id.hex())
        if info.get("submit_ts"):
            _task_latency_histogram().observe(
                max(0.0, time.time() - info["submit_ts"]),
                tags={"kind": "actor_task"})
        return {"ok": True}

    # ==================================================================
    # Streaming generators — caller side (reference: task_manager.cc:778)
    # ==================================================================
    def _register_stream(self, task_id: TaskID):
        from ray_tpu._private.streaming import ObjectRefGenerator, _StreamState

        st = _StreamState()
        self._streams[task_id] = st
        return ObjectRefGenerator(self, task_id, st)

    def _handle_streaming_yield(
        self, task_id_bin: bytes, index: int, kind: str,
        data: Optional[bytes] = None, node_id: Optional[str] = None,
    ) -> dict:
        tid = TaskID(task_id_bin)
        st = self._streams.get(tid)
        if st is None:
            return {"ok": False}  # stream abandoned — drop
        oid = ObjectID.from_index(tid, index + 1)
        rc = self._ref_counter()
        if not rc.has_reference(oid):
            rc.add_owned_object(oid)
        if kind == "inline":
            self.memory_store.put(oid, ("inline", data))
        else:
            self.memory_store.put(oid, ("plasma", node_id))
        with st.cv:
            st.arrived[index] = oid
            st.notify_locked()
            pending = len(st.arrived)
        return {"ok": True, "pending": pending}

    def _handle_streaming_credit(self, task_id_bin: bytes) -> dict:
        """Producer-side backpressure poll: how many yields sit undelivered
        in this consumer's buffer."""
        st = self._streams.get(TaskID(task_id_bin))
        if st is None:
            return {"ok": False, "pending": 0}
        with st.cv:
            return {"ok": True, "pending": len(st.arrived)}

    def _handle_streaming_done(
        self, task_id_bin: bytes, count: int, error: Optional[bytes] = None
    ) -> dict:
        tid = TaskID(task_id_bin)
        st = self._streams.get(tid)
        if st is None:
            return {"ok": False}
        with st.cv:
            if error is not None:
                err = deserialize(error)
                st.error = err.as_instanceof_cause() if isinstance(err, RayTaskError) else err
            st.total = count
            st.notify_locked()
        return {"ok": True}

    def _abandon_stream(self, task_id: TaskID) -> None:
        """Consumer dropped its ObjectRefGenerator: free undelivered yields
        and refuse further pushes (the producer stops on the first refusal)."""
        st = self._streams.pop(task_id, None)
        if st is None:
            return
        with st.cv:
            oids = list(st.arrived.values())
            st.arrived.clear()
            if st.total is None:
                st.total = st.next_index
            st.notify_locked()
        for oid in oids:
            try:
                self.free_object(oid)
            except Exception:  # noqa: BLE001
                pass

    def _fail_stream(self, task_id: TaskID, err: Exception) -> None:
        st = self._streams.get(task_id)
        if st is None:
            return
        with st.cv:
            if st.error is None and st.total is None:
                st.error = err
            st.notify_locked()

    def _fail_actor_task(self, tid: TaskID, return_oids: List[ObjectID], err: Exception) -> None:
        with self._actor_pending_lock:
            info = self._pending_actor_tasks.pop(tid, None)
            contained = self._actor_task_contained.pop(tid, [])
        self._release_contained_refs(contained)
        self._fail_stream(tid, err)
        self._record_task_event(
            tid, (info or {}).get("method", "actor_task"), "FAILED",
            kind="actor_task")
        data = serialize(err)
        for oid in return_oids:
            if not self.memory_store.contains(oid):
                self.memory_store.put(oid, ("inline", data))

    def _report_actor_fault(self, aid: str, addr: Tuple[str, int], error: str) -> None:
        self._invalidate_actor_addr(aid, addr)
        try:
            self.gcs.call_retrying(
                "ReportActorFault", actor_id=aid, worker_addr=addr, error=error
            )
        except Exception:
            pass

    async def _report_actor_fault_async(
        self, aid: str, addr: Tuple[str, int], error: str,
    ) -> None:
        self._invalidate_actor_addr(aid, addr)
        try:
            await self.gcs.acall(
                "ReportActorFault", actor_id=aid, worker_addr=addr,
                error=error, timeout=15)
        except Exception:  # noqa: BLE001 — advisory
            pass

    def _invalidate_actor_addr(self, aid: str, addr: Tuple[str, int]) -> None:
        cached = self._actor_addr_cache.get(aid)
        if cached is not None and cached[0] == addr:
            self._actor_addr_cache.pop(aid, None)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._actor_addr_cache.pop(actor_id.hex(), None)
        self.gcs.call_retrying("KillActor", actor_id=actor_id.hex(), no_restart=no_restart)

    def get_actor(self, name: str, namespace: Optional[str] = None):
        aid = self.gcs.call_retrying("GetActorByName", name=name, namespace=namespace or "default")
        if aid is None:
            raise ValueError(f"Failed to look up actor with name '{name}'")
        return ActorID.from_hex(aid)

    def cancel(self, ref: ObjectRef, force: bool = False, recursive: bool = True) -> None:
        """Cancel the task that creates ``ref`` (reference: CancelTask,
        core_worker.cc). Queued tasks are dropped before dispatch; RUNNING
        tasks get TaskCancelledError raised in their executing thread
        (force=True kills the worker process instead)."""
        tid = ref.id().task_id()
        st = self._pending_tasks.get(tid)
        if st is None:
            return
        st["cancelled"] = True  # blocks dispatch-from-queue and retries
        err = serialize(TaskCancelledError(f"Task {tid.hex()[:12]} cancelled"))
        for oid in st["spec"].return_ids():
            if not self.memory_store.contains(oid):
                self.memory_store.put(oid, ("inline", err))
        entry = st.get("entry")
        if entry is not None:  # already pushed to a worker
            try:
                get_client(entry.worker_addr).call(
                    "CancelTask", task_id_bin=tid.binary(), force=force, timeout=10
                )
            except Exception:  # noqa: BLE001
                pass
        self._record_task_event(
            tid, st["spec"].function_descriptor.repr_name, "FAILED")

    # ==================================================================
    # Placement groups
    # ==================================================================
    def create_placement_group(self, bundles, strategy, name=""):
        from ray_tpu._private.ids import PlacementGroupID

        pg_id = PlacementGroupID.from_random()
        self.gcs.call_retrying(
            "CreatePlacementGroup",
            pg_id=pg_id.hex(),
            name=name,
            bundles=bundles,
            strategy=strategy,
            creator_job=self.job_id.hex(),
        )
        return pg_id

    def remove_placement_group(self, pg_id) -> None:
        self.gcs.call_retrying("RemovePlacementGroup", pg_id=pg_id.hex())

    def placement_group_ready(self, pg_id, timeout=None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            info = self.gcs.call_retrying("GetPlacementGroup", pg_id=pg_id.hex())
            if info and info["state"] == "CREATED":
                return True
            if info and info["state"] in ("REMOVED", "INFEASIBLE"):
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)

    def get_placement_group_info(self, pg_id) -> Optional[dict]:
        return self.gcs.call_retrying("GetPlacementGroup", pg_id=pg_id.hex())

    # ==================================================================
    # Cluster info
    # ==================================================================
    def cluster_resources(self) -> Dict[str, float]:
        return self.gcs.call_retrying("GetClusterResources")["total"]

    def available_resources(self) -> Dict[str, float]:
        return self.gcs.call_retrying("GetClusterResources")["available"]

    def nodes(self) -> List[Dict[str, Any]]:
        return self.gcs.call_retrying("GetAllNodeInfo")

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        with self._actor_disp_lock:
            for d in self._actor_dispatchers.values():
                d.stop()
        self.server.stop()
        try:
            self.plasma.close()
        except Exception:
            logger.debug("plasma close failed at shutdown", exc_info=True)
        # close every RPC client this process opened: each one owns a
        # read-loop task that must be cancelled AND awaited, or asyncio
        # logs "Task was destroyed but it is pending!" at exit
        from ray_tpu._private.rpc import clear_client_cache

        for c in (self.gcs, self.raylet):
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        try:
            clear_client_cache()
        except Exception:  # noqa: BLE001
            pass
