"""RAY_TPU_DEBUG_LOCKS=1 — dynamic lock-order validation.

The static RC002 rule (tools/raycheck/lockgraph.py) models lock
acquisition order from the AST; this module validates that model against
reality. With ``RAY_TPU_DEBUG_LOCKS=1`` in the environment, the
``maybe_wrap`` calls sprinkled on the _private module locks return an
order-recording proxy instead of the bare lock:

  * every acquisition records edges  held-lock -> new-lock  into one
    process-global order graph,
  * an acquisition that would close a cycle in that graph (thread A took
    X then Y, thread B now holds Y and asks for X) raises
    :class:`LockOrderError` at the exact acquisition site instead of
    deadlocking silently in production.

Off (the default) the cost is one ``os.environ`` check at lock-creation
time and zero per-acquisition overhead — ``maybe_wrap`` returns the raw
lock object untouched.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set


class LockOrderError(RuntimeError):
    """An acquisition would create a lock-order cycle (potential deadlock)."""


def enabled() -> bool:
    return os.environ.get("RAY_TPU_DEBUG_LOCKS", "0").strip() in (
        "1", "true", "on")


class _OrderGraph:
    """Process-global acquisition-order graph, guarded by its own lock
    (which is never itself wrapped)."""

    def __init__(self) -> None:
        self._edges: Dict[str, Set[str]] = {}
        self._guard = threading.Lock()
        self._held = threading.local()

    def held_stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _path_exists(self, src: str, dst: str) -> bool:
        stack, seen = [src], {src}
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            for nxt in self._edges.get(n, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

    def before_acquire(self, name: str) -> None:
        held = self.held_stack()
        if not held:
            return
        with self._guard:
            for h in held:
                if h == name:
                    continue  # re-entrant acquire: not an order edge
                # adding h -> name while name -> ... -> h already exists
                # means two code paths take these locks in opposite
                # orders — the cycle that deadlocks under the right race
                if self._path_exists(name, h):
                    order = " -> ".join(held + [name])
                    raise LockOrderError(
                        f"lock-order cycle: acquiring {name!r} while "
                        f"holding {held!r} (this thread: {order}), but "
                        f"{name!r} -> {h!r} was previously acquired in "
                        f"the opposite order elsewhere")
                self._edges.setdefault(h, set()).add(name)

    def after_acquire(self, name: str) -> None:
        self.held_stack().append(name)

    def after_release(self, name: str) -> None:
        st = self.held_stack()
        # release may happen on another thread or out of order — tolerate
        if name in st:
            st.reverse()
            st.remove(name)
            st.reverse()

    def reset(self) -> None:
        """Test hook: forget recorded orders."""
        with self._guard:
            self._edges.clear()


_graph = _OrderGraph()


def order_graph() -> _OrderGraph:
    return _graph


class DebugLock:
    """Order-recording proxy over a Lock/RLock. Supports the full
    surface the codebase uses: ``with``, acquire(timeout=...), release,
    locked."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            _graph.before_acquire(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _graph.after_acquire(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        _graph.after_release(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self.name} over {self._lock!r}>"


def maybe_wrap(lock, name: str):
    """Wrap ``lock`` in a DebugLock when RAY_TPU_DEBUG_LOCKS=1; otherwise
    return it untouched (zero overhead on the hot path)."""
    if enabled():
        return DebugLock(lock, name)
    return lock
