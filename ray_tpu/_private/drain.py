"""Graceful node drain — shared protocol constants + client helper.

Reference: the `DrainNode` RPC of gcs_service.proto carries a reason
(`DRAIN_NODE_REASON_PREEMPTION` / `DRAIN_NODE_REASON_IDLE_TERMINATION`)
and a deadline; autoscaler_state_service and node_manager cooperate so a
draining node stops taking work, finishes what it can, and deregisters
before the machine disappears. TPU capacity makes this a first-class
path: a preempted pod slice gets a short notice and then every host in
it vanishes at once.

Drain lifecycle (our implementation):

  DrainNode(node_id, reason, deadline_s)          [any client -> GCS]
    GCS: node -> DRAINING, published on node_state, NODE_DRAIN_START
    GCS -> raylet Drain(reason, deadline_s): stop granting leases,
      redirect queued/new lease requests (spillback), let in-flight
      tasks run out
    GCS: migrate each ALIVE actor — worker DrainActor (finish accepted
      tasks, stop accepting) then restart per max_restarts elsewhere,
      watchers woken by the published actor_state event
    raylet: once task leases drain (or the deadline hits) push primary
      object copies to a surviving node, then NodeDrainComplete
    GCS: node -> dead, NODE_DRAIN_COMPLETE, actors already moved

A node is never stuck DRAINING: the GCS health watchdog force-completes
past deadline + grace, and a restarted GCS relearns the draining flag
from raylet heartbeats.
"""

from __future__ import annotations

from typing import Optional

# Drain reasons (mirroring autoscaler.proto's DrainNodeReason values).
REASON_PREEMPTION = "DRAIN_NODE_REASON_PREEMPTION"
REASON_IDLE_TERMINATION = "DRAIN_NODE_REASON_IDLE_TERMINATION"
# cluster teardown: quiesce only — skip the object push, the whole
# cluster is going away
REASON_CLUSTER_SHUTDOWN = "DRAIN_NODE_REASON_CLUSTER_SHUTDOWN"

# Event-bus types emitted by the GCS (rstate.list_events(etype=...)).
EVENT_DRAIN_START = "NODE_DRAIN_START"
EVENT_DRAIN_COMPLETE = "NODE_DRAIN_COMPLETE"


def drain_node(gcs_client, node_id: str, reason: str = REASON_PREEMPTION,
               deadline_s: Optional[float] = None,
               timeout: float = 10.0) -> dict:
    """Ask the GCS to gracefully drain ``node_id``. Returns the GCS
    reply ({"ok", "draining": [node_ids...]}); a preemption reason on a
    slice member drains the whole slice."""
    return gcs_client.call(
        "DrainNode", node_id=node_id, reason=reason,
        deadline_s=deadline_s, timeout=timeout,
    )
