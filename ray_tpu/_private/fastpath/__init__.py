"""Control-plane codec fast path: native C extension with a byte-identical
pure-Python fallback.

Reference analogue: the reference executes its per-call hot loop in C++
with the GIL dropped (src/ray/_raylet.pyx:2942, src/ray/rpc/); this module
is that native layer for the frame/codec work of ray_tpu's Python control
plane. Consumers import the module-level functions — whichever backend won
selection at import time is transparent:

    pack_header / unpack_header     RPC frame header ([u32][u64][u8])
    encode_body / decode_body       out-of-band body framing
    write_body_into                 single-pass frame layout into a mapping
                                    (GIL-released memcpy on the C backend)
    build_frame                     header + small body in one allocation
    id_from_index                   ObjectID::FromIndex derivation

Selection (``RAY_TPU_FASTPATH``):
    unset / "1" / "auto"  build+load the C extension if a compiler is
                          available; silently fall back to Python otherwise
    "0"                   force the pure-Python fallback
    "require"             fail loudly if the C extension cannot load
                          (CI guard against silent fallback)

The build is make-driven (src/fastpath/Makefile, same pattern as
src/object_store) into ``_build/`` next to this file, serialized across
processes with an flock so a cluster boot (driver + gcs + raylet + workers
importing concurrently) compiles once.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import sysconfig

from ray_tpu._private.fastpath import _pyimpl

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
# RAY_TPU_FASTPATH_BUILD_DIR: alternate build/load directory — the ASan
# test builds an instrumented .so into a temp dir and points a child
# interpreter here, without clobbering the normal build
_BUILD_DIR = os.environ.get("RAY_TPU_FASTPATH_BUILD_DIR") or \
    os.path.join(_DIR, "_build")
# ABI-tagged filename + built with THIS interpreter's headers: a 3.10
# venv and a 3.13 system python keep separate extensions — loading a
# mismatched ABI would be undefined behavior, not an ImportError
_SO_PATH = os.path.join(
    _BUILD_DIR,
    "ray_tpu_fastpath" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so"))


def _repo_src_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(_DIR)))
    return os.path.join(root, "src", "fastpath")


def _needs_build(src: str) -> bool:
    if not os.path.exists(src):
        return False  # installed without sources: use what exists
    return not os.path.exists(_SO_PATH) or (
        os.path.getmtime(_SO_PATH) < os.path.getmtime(src)
    )


def _build_locked() -> bool:
    """Build the extension under an flock (many processes import this
    module at cluster boot; exactly one compiles)."""
    src_dir = _repo_src_dir()
    src = os.path.join(src_dir, "fastpath.c")
    if not _needs_build(src):
        return os.path.exists(_SO_PATH)
    os.makedirs(_BUILD_DIR, exist_ok=True)
    lock_path = os.path.join(_BUILD_DIR, ".build.lock")
    try:
        import fcntl

        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if _needs_build(src):  # re-check: the lock winner built it
                    subprocess.run(
                        ["make", "-C", src_dir,
                         f"PYTHON={sys.executable}",
                         f"BUILD_DIR={_BUILD_DIR}"],
                        check=True, capture_output=True, timeout=120,
                    )
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
    except Exception as e:  # noqa: BLE001 — no compiler, make missing, ...
        logger.debug("fastpath build failed (%s); using Python fallback", e)
        return os.path.exists(_SO_PATH)
    return os.path.exists(_SO_PATH)


def _load_c():
    """Load the ABI-tagged extension from _build/."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("ray_tpu_fastpath", _SO_PATH)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {_SO_PATH}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _select():
    mode = os.environ.get("RAY_TPU_FASTPATH", "auto").strip().lower()
    if mode in ("0", "false", "off", "python"):
        return _pyimpl
    try:
        if _build_locked():
            return _load_c()
        raise ImportError("fastpath extension not built")
    except Exception as e:  # noqa: BLE001
        if mode == "require":
            raise ImportError(
                f"RAY_TPU_FASTPATH=require but the C extension is "
                f"unavailable: {e}"
            ) from e
        logger.debug("fastpath C backend unavailable (%s); using Python", e)
        return _pyimpl


_impl = _select()

BACKEND: str = _impl.BACKEND
NOGIL_THRESHOLD: int = _impl.NOGIL_THRESHOLD
pack_header = _impl.pack_header
unpack_header = _impl.unpack_header
encode_body = _impl.encode_body
decode_body = _impl.decode_body
write_body_into = _impl.write_body_into
build_frame = _impl.build_frame
id_from_index = _impl.id_from_index


def backend() -> str:
    """"c" when the native extension serves the hot loop, else "python"."""
    return BACKEND


def available_backends() -> dict:
    """name -> impl module, for the parity test. The Python fallback is
    always present; "c" appears when the extension can load (built here
    if a compiler exists)."""
    out = {"python": _pyimpl}
    if BACKEND == "c":
        out["c"] = _impl
    else:
        try:
            if _build_locked():
                out["c"] = _load_c()
        except Exception:  # noqa: BLE001 — parity test skips the C half
            pass
    return out
