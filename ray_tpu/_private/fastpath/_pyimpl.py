"""Pure-Python fallback for the native control-plane codec.

Byte-identical to src/fastpath/fastpath.c — tests/test_fastpath_parity.py
round-trips every function through both backends and asserts equal output.
Change the wire layout in BOTH places or not at all.

Layouts:
    frame header:  [u32 total][u64 call_id][u8 kind]   (little-endian)
    OOB body:      [u32 meta_len][meta][u32 nbuf]([u64 blen][payload])*
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

BACKEND = "python"
# mirror of FASTPATH_NOGIL_THRESHOLD — meaningless here (the fallback
# cannot drop the GIL) but kept so both backends expose the same surface
NOGIL_THRESHOLD = 64 * 1024

_HDR = struct.Struct("<IQB")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def pack_header(total: int, call_id: int, kind: int) -> bytes:
    if not 0 <= kind <= 255:
        raise ValueError("kind must be 0..255")
    return _HDR.pack(total, call_id, kind)


def unpack_header(buf) -> Tuple[int, int, int]:
    if len(buf) < 13:
        raise ValueError("frame header needs 13 bytes")
    total, call_id, kind = _HDR.unpack_from(buf, 0)
    return total, call_id, kind


def encode_body(meta, bufs) -> bytes:
    out = bytearray(8 + len(meta) + sum(8 + b.nbytes if hasattr(b, "nbytes")
                                        else 8 + len(b) for b in bufs))
    write_body_into(out, meta, bufs)
    return bytes(out)


def write_body_into(dest, meta, bufs) -> int:
    mv = memoryview(dest)
    off = 0
    _U32.pack_into(mv, off, len(meta))
    off += 4
    mv[off: off + len(meta)] = meta
    off += len(meta)
    _U32.pack_into(mv, off, len(bufs))
    off += 4
    for b in bufs:
        blen = b.nbytes if hasattr(b, "nbytes") else len(b)
        _U64.pack_into(mv, off, blen)
        off += 8
        mv[off: off + blen] = b
        off += blen
    return off


def decode_body(body) -> Tuple[Any, List[Any]]:
    mv = memoryview(body)
    if len(mv) < 8:
        raise ValueError("truncated out-of-band body")
    (meta_len,) = _U32.unpack_from(mv, 0)
    off = 4
    if off + meta_len + 4 > len(mv):
        raise ValueError("truncated out-of-band body")
    meta = mv[off: off + meta_len]
    off += meta_len
    (nbuf,) = _U32.unpack_from(mv, off)
    off += 4
    buffers = []
    for _ in range(nbuf):
        if off + 8 > len(mv):
            raise ValueError("truncated out-of-band body")
        (blen,) = _U64.unpack_from(mv, off)
        off += 8
        if off + blen > len(mv):
            raise ValueError("truncated out-of-band body")
        buffers.append(mv[off: off + blen])
        off += blen
    return meta, buffers


def build_frame(call_id: int, kind: int, body) -> bytes:
    if not 0 <= kind <= 255:
        raise ValueError("kind must be 0..255")
    blen = body.nbytes if hasattr(body, "nbytes") else len(body)
    return _HDR.pack(blen, call_id, kind) + bytes(body)


def id_from_index(prefix, index: int) -> bytes:
    return bytes(prefix) + _U32.pack(index)
