"""GCS — the head-node control plane process.

Reference: src/ray/gcs/gcs_server.h:99 (GcsServer composes node/actor/job/
PG/KV managers), gcs_node_manager.cc:102 (register), gcs_actor_manager.cc:314
(register actor) / :433 (create) / :1721 (SchedulePendingActors),
gcs_health_check_manager.h:46 (liveness), gcs_kv_manager.h (KV).

One asyncio process: tables in memory, optional file persistence for the KV
table, periodic health checks that mark silent raylets dead, actor
scheduling via raylet lease RPCs, and placement-group 2PC (PREPARE/COMMIT
like node_manager.proto:514-519).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import pickle
import struct
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import config
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu.observability import timeline as obs_timeline

logger = logging.getLogger("ray_tpu.gcs")


# ---------------------------------------------------------------------------
# Tables
# ---------------------------------------------------------------------------
@dataclass
class NodeInfo:
    node_id: str
    address: Tuple[str, int]  # raylet RPC addr
    store_socket: str
    total_resources: Dict[str, float]
    available_resources: Dict[str, float]
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    is_head: bool = False
    # graceful drain (reference: DrainNode + autoscaler.proto reasons):
    # a DRAINING node is still alive — in-flight work finishes — but
    # takes no new leases/placements and is published so schedulers
    # route around it before it dies
    draining: bool = False
    drain_reason: str = ""
    drain_deadline: float = 0.0  # monotonic; 0 = not draining
    drain_started_at: float = 0.0
    labels: Dict[str, str] = field(default_factory=dict)
    agent_port: int = 0  # per-node dashboard agent (dashboard/agent.py)
    # autoscaler signal (reference: GcsAutoscalerStateManager)
    pending_shapes: List[Dict[str, float]] = field(default_factory=list)
    num_leases: int = 0
    idle_since: Optional[float] = None


@dataclass
class ActorInfo:
    actor_id: str
    job_id: str
    name: Optional[str]
    namespace: str
    state: str  # PENDING, ALIVE, RESTARTING, DEAD
    serialized_spec: bytes  # creation task spec (class + args + opts)
    owner_addr: Optional[Tuple[str, int]]
    worker_addr: Optional[Tuple[str, int]] = None
    node_id: Optional[str] = None
    worker_id: Optional[str] = None
    max_restarts: int = 0
    num_restarts: int = 0
    resources: Dict[str, float] = field(default_factory=dict)
    detached: bool = False
    death_cause: str = ""
    version: int = 0  # bumped on every state change
    pg_id: Optional[str] = None
    bundle_index: int = -1
    # num_cpus defaulted: CPU counts for scheduling creation only, not held
    # while alive (reference actor resource semantics)
    cpu_scheduling_only: bool = False
    # a lease request for this actor is queued at some raylet — its shape
    # already shows in that node's pending_shapes (autoscaler dedupe)
    lease_in_flight: bool = False
    # workers tainted by a runtime env are dedicated to it
    runtime_env_hash: str = ""
    # scheduling strategy (reference: node-affinity / node-label policies)
    scheduling_kind: str = "DEFAULT"
    affinity_node_id: Optional[str] = None
    strategy_soft: bool = False
    node_labels: Optional[Dict[str, str]] = None



@dataclass
class PlacementGroupInfo:
    pg_id: str
    name: str
    strategy: str  # PACK, SPREAD, STRICT_PACK, STRICT_SPREAD
    bundles: List[Dict[str, float]]
    state: str  # PENDING, CREATED, REMOVED
    # bundle index -> (node_id, lease)
    bundle_nodes: Dict[int, str] = field(default_factory=dict)
    creator_job: str = ""


class GcsServer:
    def __init__(self, port: int, storage_path: str = ""):
        self.server = RpcServer(port=port, name="gcs")
        self.storage_path = storage_path
        self.nodes: Dict[str, NodeInfo] = {}
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}
        self.placement_groups: Dict[str, PlacementGroupInfo] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> value
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self._job_counter = 0
        self._raylet_clients: Dict[str, RpcClient] = {}
        self._actor_events: Dict[str, asyncio.Event] = {}
        self._node_version = 0
        # observability (bounded): pushed metrics, task events, log lines
        from collections import deque

        self.metrics_by_producer: Dict[str, Tuple[List[dict], float]] = {}
        self.task_events: Any = deque(maxlen=20000)
        self.log_buffer: Any = deque(maxlen=50000)
        self._log_seq = 0
        self.metrics_http_port = 0
        # pubsub (reference: src/ray/pubsub/publisher.h:357 — long-poll
        # publisher with per-channel cursors): channel -> deque of
        # (seq, key, payload); subscribers long-poll past their cursor
        self.pubsub: Dict[str, Any] = {}
        self._pubsub_seq = 0
        self._pubsub_waiters: Any = None  # asyncio.Condition, lazy
        # channel -> seq of the NEWEST event the bounded ring evicted; a
        # subscriber whose cursor is below this floor has a gap it can
        # never replay and must resync (Subscribe returns the floor)
        self.pubsub_dropped: Dict[str, int] = {}
        # event bus + trace aggregation (reference: GcsTaskManager-style
        # bounded history, see observability/aggregator.py)
        from ray_tpu.observability.aggregator import EventAggregator

        self.cluster_events = EventAggregator()
        # the GCS's own bus events (lifecycle marks, drain/restart
        # events) ingest through a local sink — no RPC to itself — and
        # its shard/debug-dir identity is its own address
        from ray_tpu.observability import dump as obs_dump
        from ray_tpu.observability import events as obs_events

        obs_events.set_process_ident("gcs")
        obs_events.set_local_sink(self.cluster_events.add)
        obs_dump.set_run_tag(f"127.0.0.1-{port}")
        obs_dump.install("gcs")
        self._last_fanout_dump = 0.0
        # graceful drain bookkeeping: per-node orchestration tasks,
        # completion events, and the bounded directory of primary
        # copies pushed off drained nodes (oid_bin -> node_id)
        from collections import OrderedDict

        self._drain_migrations: Dict[str, Any] = {}
        self._drain_done_events: Dict[str, asyncio.Event] = {}
        self.moved_objects: Any = OrderedDict()
        # lease, not a latch: the autoscaler re-asserts every reconcile
        # round; if it dies, the flag expires and raylets fall back to
        # fail-fast infeasible errors instead of queueing forever
        self.autoscaler_enabled_until = 0.0
        self._dirty = False
        self._needs_replay_reschedule = False
        # per-NODE creation gates (asyncio.Semaphore, loop-affine): the
        # admission bound on in-flight lease+spawn+CreateActor pipelines
        # scales with the cluster instead of throttling a multi-node
        # burst to one node's budget
        self._actor_create_gates: Dict[str, Any] = {}
        self._last_prestart = 0.0
        self._wal = None  # lazily-opened append handle
        self._wal_records = 0
        self._wal_degraded = False  # an append failed since last compact
        self._wal_seq = 0  # records appended this process lifetime
        self._wal_synced = 0  # highest seq durable (group fsync/snapshot)
        self._wal_sync_lock: Optional[asyncio.Lock] = None  # loop-affine
        self._load_persisted()
        replayed, had_wal = self._replay_wal()
        if replayed:
            logger.info("replayed %d WAL records", self._wal_records)
            for a in self.actors.values():
                a.lease_in_flight = False
            # a restart restored state (possibly WAL-only, before any
            # snapshot existed): pending work needs rescheduling
            self._needs_replay_reschedule = True
        if had_wal:
            # fold into a fresh snapshot + truncate — ALSO when zero
            # records replayed: a torn first record must not linger as
            # garbage that later appends would land after
            self._dirty = True
            self._compact()
        self.server.register_instance(self)
        # pubsub long-poll parks for its whole timeout by design — exempt
        # it from the transport's slow-async-handler warning
        self.server.register("Subscribe", self.Subscribe, long_poll=True)
        self.server.pre_response = self._wal_barrier

    # ------------------------------------------------------------------
    # persistence (file-backed snapshot of the durable tables: KV,
    # actors, placement groups, jobs — a restarted GCS replays them and
    # resumes; reference: gcs_init_data.h replaying from Redis,
    # gcs_table_storage.h:200). Nodes are NOT persisted: raylets get
    # "reregister" on their next heartbeat and repopulate the table.
    # ------------------------------------------------------------------
    def _load_persisted(self) -> None:
        if not (self.storage_path and os.path.exists(self.storage_path)):
            return
        try:
            with open(self.storage_path, "rb") as f:
                snap = pickle.load(f)
        except Exception:
            logger.exception("failed to load persisted state")
            return
        if isinstance(snap, dict) and "kv" in snap and "actors" in snap:
            self.kv = snap["kv"]
            self._load_blobs()
            self.actors = snap.get("actors", {})
            self.named_actors = snap.get("named_actors", {})
            self.placement_groups = snap.get("placement_groups", {})
            self.jobs = snap.get("jobs", {})
            self._job_counter = snap.get("job_counter", 0)
            # in-flight markers are meaningless across a restart
            for a in self.actors.values():
                a.lease_in_flight = False
            n_live = sum(1 for a in self.actors.values()
                         if a.state != "DEAD")
            logger.info(
                "replayed persisted state: %d actors (%d live), %d PGs, "
                "%d jobs", len(self.actors), n_live,
                len(self.placement_groups), len(self.jobs))
            # no loop is running during __init__ — run() kicks this off
            self._needs_replay_reschedule = True
        else:  # pre-snapshot format: bare KV dict
            self.kv = snap

    async def _reschedule_replayed(self) -> None:
        """PENDING/RESTARTING actors from before the restart need a new
        scheduling attempt — wait for raylets to re-register first."""
        deadline = time.monotonic() + 60
        while not self.nodes and time.monotonic() < deadline:
            await asyncio.sleep(0.2)
        for actor in self.actors.values():
            if actor.state in ("PENDING", "RESTARTING"):
                logger.info("rescheduling replayed actor %s",
                            actor.actor_id[:12])
                asyncio.ensure_future(self._schedule_actor(actor))
        for pg in self.placement_groups.values():
            if pg.state == "PENDING":
                logger.info("rescheduling replayed placement group %s",
                            pg.pg_id[:12])
                asyncio.ensure_future(self._schedule_pg(pg))
        # ALIVE actors whose node died WHILE the GCS was down: the dead
        # node never re-registers, so the health checker (which only
        # scans registered nodes) would never fail them over — give
        # every live node a grace window to come back, then treat the
        # missing ones as dead.
        grace = max(5.0, 3 * config.raylet_heartbeat_period_ms / 1000.0)
        await asyncio.sleep(grace)
        for actor in list(self.actors.values()):
            if actor.state == "ALIVE" and actor.node_id and (
                    actor.node_id not in self.nodes
                    or not self.nodes[actor.node_id].alive):
                logger.warning(
                    "replayed actor %s was on node %s which did not "
                    "re-register; failing over", actor.actor_id[:12],
                    actor.node_id[:12])
                await self._handle_actor_failure(
                    actor, "node lost during GCS downtime")

    # KV namespaces holding large immutable blobs (runtime-env packages)
    # are persisted as write-once files beside the snapshot, keeping the
    # snapshot itself small enough to write synchronously at critical
    # mutations (a 100MB working_dir must not re-serialize per flush).
    _BLOB_NAMESPACES = ("runtime_env_packages",)

    # -- write-ahead log (reference: redis_store_client.h semantics —
    # every durable table mutation is written through BEFORE the state
    # is acknowledged; here an fsync'd append log + periodic snapshot
    # compaction replaces Redis) --------------------------------------
    _WAL_COMPACT_RECORDS = 2000

    def _wal_path(self) -> str:
        return self.storage_path + ".wal"

    def _wal_file(self):
        if self._wal is None:
            self._wal = open(self._wal_path(), "ab")
        return self._wal

    def _log(self, kind: str, *payload: Any) -> None:
        """Append one durable mutation to the WAL. The append is flushed
        to the OS but NOT fsync'd here: the RPC layer awaits
        ``_wal_barrier`` before sending any response, so one group
        fsync covers every record the current batch of handlers
        appended — a crash at ANY point after an ack still replays the
        mutation on restart, without a disk sync per mutation."""
        if not self.storage_path:
            return
        try:
            rec = pickle.dumps((kind, payload))
            f = self._wal_file()
            f.write(struct.pack("<I", len(rec)))
            f.write(rec)
            f.flush()
        except Exception:
            logger.exception("WAL append failed")
            # the mutation is acknowledged but not on disk: mark for the
            # compaction safety net so a later snapshot captures it, and
            # degrade to FULL actor records (slim actor_state deltas
            # need their base record to replay) until compaction
            self._dirty = True
            self._wal_degraded = True
            return
        self._wal_seq += 1
        self._wal_records += 1
        if self._wal_records >= self._WAL_COMPACT_RECORDS:
            self._compact()

    async def _wal_barrier(self) -> None:
        """Group-commit fsync (the RpcServer ``pre_response`` hook):
        make every WAL record appended so far durable before any
        handler's ack leaves the process. Concurrent barriers coalesce
        behind one lock — the first fsync covers the whole batch and
        the rest return without touching the disk."""
        if not self.storage_path or self._wal_synced >= self._wal_seq:
            return
        if self._wal_sync_lock is None:
            self._wal_sync_lock = asyncio.Lock()
        async with self._wal_sync_lock:
            seq = self._wal_seq
            if self._wal_synced >= seq:
                return
            f = self._wal
            if f is None:
                return  # compaction just truncated: state is in the snapshot
            try:
                fd = f.fileno()
                await asyncio.get_event_loop().run_in_executor(
                    None, os.fsync, fd)
            except Exception:  # noqa: BLE001
                if self._wal_synced >= seq:
                    return  # compaction raced the fsync; snapshot has it
                logger.exception("WAL group fsync failed")
                self._dirty = True
                self._wal_degraded = True
                return
            if self._wal_synced < seq:
                self._wal_synced = seq

    def _compact(self) -> None:
        """Fold the WAL into a fresh snapshot and truncate it. Crash
        between the snapshot replace and the truncate replays WAL
        records on top of a snapshot that already contains them —
        harmless, records are full-row idempotent."""
        self._dirty = True
        if not self._flush():
            # snapshot failed (e.g. disk full): keep the WAL — truncating
            # would discard the only durable copy of acknowledged state
            return
        # the fsync'd snapshot now holds every mutation applied so far;
        # advance the group-commit cursor BEFORE closing the file so a
        # barrier racing the close re-checks and finds itself covered
        self._wal_synced = self._wal_seq
        try:
            if self._wal is not None:
                self._wal.close()
            self._wal = open(self._wal_path(), "wb")
            self._wal.close()
            self._wal = None
        except Exception:
            logger.exception("WAL truncate failed")
        self._wal_records = 0
        self._wal_degraded = False

    def _replay_wal(self) -> Tuple[int, bool]:
        """Returns (records replayed, wal file existed). A torn or
        corrupt tail stops replay at the last intact record; the caller
        compacts, which truncates the garbage (records beyond a torn
        length prefix are unrecoverable — the framing chain is broken)."""
        path = self._wal_path()
        if not os.path.exists(path):
            return 0, False
        n = 0
        try:
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + 4 <= len(data):
                (ln,) = struct.unpack_from("<I", data, off)
                if off + 4 + ln > len(data):
                    break  # torn tail record from a mid-write crash
                kind, payload = pickle.loads(data[off + 4: off + 4 + ln])
                self._apply_wal(kind, payload)
                off += 4 + ln
                n += 1
        except Exception:
            logger.exception("WAL replay failed at record %d", n)
        self._wal_records = n
        return n, True

    # the mutable ActorInfo fields a state transition can touch — the
    # slim "actor_state" record carries only these, not the (possibly
    # huge) serialized creation spec logged once at registration
    _ACTOR_STATE_FIELDS = ("state", "version", "worker_addr", "node_id",
                           "worker_id", "num_restarts", "death_cause")

    def _log_actor_state(self, a: "ActorInfo") -> None:
        if self._wal_degraded:
            # a lost append may have been this actor's base record;
            # full rows keep replay self-contained until compaction
            self._log("actor", a)
            return
        self._log("actor_state", a.actor_id,
                  {f: getattr(a, f) for f in self._ACTOR_STATE_FIELDS})

    def _apply_wal(self, kind: str, payload: tuple) -> None:
        if kind == "actor":
            a = payload[0]
            self.actors[a.actor_id] = a
        elif kind == "actor_state":
            aid, fields = payload
            a = self.actors.get(aid)
            if a is None:
                logger.warning("WAL actor_state for unknown actor %s",
                               aid[:12])
            else:
                for f, v in fields.items():
                    setattr(a, f, v)
        elif kind == "named":
            ns, name, aid = payload
            self.named_actors[(ns, name)] = aid
        elif kind == "named_del":
            ns, name = payload
            self.named_actors.pop((ns, name), None)
        elif kind == "pg":
            pg = payload[0]
            self.placement_groups[pg.pg_id] = pg
        elif kind == "job":
            jid, info = payload
            self.jobs[jid] = info
        elif kind == "job_counter":
            self._job_counter = max(self._job_counter, payload[0])
        elif kind == "kv":
            ns, key, value = payload
            self.kv.setdefault(ns, {})[key] = value
        elif kind == "kv_blob":
            ns, key = payload
            try:
                with open(os.path.join(self._blob_dir(), ns + "." + key),
                          "rb") as f:
                    self.kv.setdefault(ns, {})[key] = f.read()
            except OSError:
                logger.warning("WAL blob %s/%s missing", ns, key)
        elif kind == "kv_del":
            ns, key = payload
            self.kv.get(ns, {}).pop(key, None)
        else:
            logger.warning("unknown WAL record kind %r", kind)

    def _log_kv(self, ns: str, key: str, value: bytes) -> None:
        """KV mutations route large blob namespaces to the side files
        (content-addressed, write-once) so the WAL stays small."""
        if ns in self._BLOB_NAMESPACES and self.storage_path:
            bd = self._blob_dir()
            os.makedirs(bd, exist_ok=True)
            p = os.path.join(bd, ns + "." + key)
            if not os.path.exists(p):
                with open(p + ".tmp", "wb") as f:
                    f.write(value)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(p + ".tmp", p)
            self._log("kv_blob", ns, key)
        else:
            self._log("kv", ns, key, value)

    def _blob_dir(self) -> str:
        return self.storage_path + ".blobs"

    def _flush(self) -> bool:
        if not (self.storage_path and self._dirty):
            return False
        self._dirty = False
        kv_snap: Dict[str, Any] = {}
        try:
            for ns, table in self.kv.items():
                if ns in self._BLOB_NAMESPACES:
                    bd = self._blob_dir()
                    os.makedirs(bd, exist_ok=True)
                    for key, blob in table.items():
                        p = os.path.join(bd, ns + "." + key)
                        if not os.path.exists(p):  # content-addressed
                            with open(p + ".tmp", "wb") as f:
                                f.write(blob)
                            os.replace(p + ".tmp", p)
                    kv_snap[ns] = {"__blob_keys__": list(table.keys())}
                else:
                    kv_snap[ns] = table
            snap = {
                "kv": kv_snap,
                "actors": self.actors,
                "named_actors": self.named_actors,
                "placement_groups": self.placement_groups,
                "jobs": self.jobs,
                "job_counter": self._job_counter,
            }
            tmp = self.storage_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(snap, f)
                f.flush()
                os.fsync(f.fileno())  # the WAL is truncated on the
                # strength of this snapshot — it must actually be on disk
            os.replace(tmp, self.storage_path)
        except Exception:
            logger.exception("state snapshot failed")
            self._dirty = True
            return False
        return True

    def _load_blobs(self) -> None:
        for ns, table in list(self.kv.items()):
            if isinstance(table, dict) and "__blob_keys__" in table:
                loaded = {}
                bd = self._blob_dir()
                for key in table["__blob_keys__"]:
                    try:
                        with open(os.path.join(bd, ns + "." + key),
                                  "rb") as f:
                            loaded[key] = f.read()
                    except OSError:
                        logger.warning("blob %s/%s missing", ns, key)
                self.kv[ns] = loaded

    async def _flush_loop(self) -> None:
        # periodic compaction safety net: bounds WAL replay time even
        # under a steady mutation trickle that never hits the record cap
        while True:
            await asyncio.sleep(30.0)
            if self._wal_records or self._dirty:
                self._compact()

    def _loop_handle(self):
        """Clients bound to the GCS's OWN event loop (rpc.LoopHandle):
        an ``acall`` from a handler runs in-line instead of paying two
        cross-thread handoffs to the global client loop per control
        RPC — on a 1-core host that is a measurable slice of every
        actor-creation pipeline."""
        from ray_tpu._private.rpc import LoopHandle

        h = getattr(self, "_loop_handle_cached", None)
        if h is None or h.loop is not asyncio.get_event_loop():
            h = self._loop_handle_cached = LoopHandle(
                asyncio.get_event_loop())
        return h

    def _raylet(self, node_id: str) -> RpcClient:
        c = self._raylet_clients.get(node_id)
        if c is None:
            node = self.nodes[node_id]
            c = RpcClient(node.address[0], node.address[1],
                          self._loop_handle())
            self._raylet_clients[node_id] = c
        return c

    _WORKER_CLIENT_CACHE_MAX = 128

    def _worker_client(self, addr: Tuple[str, int]) -> RpcClient:
        """LRU-bounded worker connections (CreateActor / KillActor):
        creation previously opened + tore down a fresh TCP connection
        per actor — connect latency inside every gated pipeline slot and
        fd churn at 2k-actor bursts. Bounded so a 40k-actor lifetime
        cannot pin 40k sockets; evicted (and dead-worker) clients close
        asynchronously and a later use simply reconnects."""
        cache = getattr(self, "_worker_clients", None)
        if cache is None:
            from collections import OrderedDict

            cache = self._worker_clients = OrderedDict()
        c = cache.get(addr)
        if c is None:
            c = cache[addr] = RpcClient(addr[0], addr[1],
                                        self._loop_handle())
        cache.move_to_end(addr)
        if len(cache) > self._WORKER_CLIENT_CACHE_MAX:
            # evict oldest IDLE clients only: closing a client with an
            # in-flight CreateActor/KillActor would fail that call
            # spuriously (multi-node gates can exceed the cap in
            # concurrent pipelines — the cache then temporarily runs
            # over and shrinks once those calls complete)
            for old_addr in list(cache):
                if len(cache) <= self._WORKER_CLIENT_CACHE_MAX:
                    break
                old = cache[old_addr]
                if old is c or old._pending:
                    continue
                del cache[old_addr]
                try:
                    old.close()
                except Exception:  # noqa: BLE001
                    pass
        return c

    # ------------------------------------------------------------------
    # Node management
    # ------------------------------------------------------------------
    async def RegisterNode(
        self,
        node_id: str,
        address: Tuple[str, int],
        store_socket: str,
        total_resources: Dict[str, float],
        is_head: bool = False,
        labels: Optional[Dict[str, str]] = None,
        agent_port: int = 0,
    ) -> dict:
        self.nodes[node_id] = NodeInfo(
            node_id=node_id,
            address=tuple(address),
            store_socket=store_socket,
            total_resources=dict(total_resources),
            available_resources=dict(total_resources),
            is_head=is_head,
            labels=labels or {},
            agent_port=agent_port,
        )
        self._node_version += 1
        logger.info("node %s registered: %s", node_id[:12], total_resources)
        return {"ok": True}

    async def Heartbeat(
        self, node_id: str, available_resources: Dict[str, float],
        pending_shapes: Optional[List[Dict[str, float]]] = None,
        num_leases: int = 0,
        draining: bool = False,
        drain_remaining_s: float = 0.0,
        drain_reason: str = "",
    ) -> dict:
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "reregister": True}
        node.last_heartbeat = time.monotonic()
        node.available_resources = dict(available_resources)
        node.pending_shapes = list(pending_shapes or [])
        node.num_leases = num_leases
        # idle tracking for scale-down: a node is idle when it holds no
        # leases and has no queued demand
        if num_leases == 0 and not node.pending_shapes:
            if node.idle_since is None:
                node.idle_since = time.monotonic()
        else:
            node.idle_since = None
        if not node.alive:
            if draining:
                # a final heartbeat from a raylet whose drain we already
                # completed (it is exiting): don't resurrect the node —
                # and don't re-enter DRAINING, which would replay the
                # completion through the watchdog
                return {"ok": True, "shutdown": True}
            node.alive = True
            # a stale draining flag (the node died mid-drain and was
            # force-completed) must not revive the node as DRAINING:
            # this resurrection is a plain health-check recovery, so it
            # re-enters ALIVE — DrainNode re-issues a drain if one is
            # still wanted
            node.draining = False
            self._node_version += 1
        if draining and not node.draining:
            # a GCS restarted mid-drain relearns the DRAINING state from
            # the raylet's heartbeats (nodes aren't persisted); the
            # raylet keeps driving its own drain and will send
            # NodeDrainComplete — no new orchestration task here, the
            # health watchdog bounds a raylet that dies first
            self._enter_draining(node, drain_reason, drain_remaining_s)
        # piggyback the cluster resource view so raylets can spill leases
        # to other nodes (reference: ray_syncer.h:91 resource broadcast)
        reply = {"ok": True, "cluster": self._cluster_view(),
                 "autoscaling":
                     time.monotonic() < self.autoscaler_enabled_until}
        if node.draining and not draining:
            # the GCS knows the node is draining but the raylet doesn't
            # (the Drain RPC was lost): re-issue the instruction on the
            # heartbeat reply
            reply["drain"] = {
                "reason": node.drain_reason,
                "deadline_s": max(0.0,
                                  node.drain_deadline - time.monotonic()),
            }
        return reply

    async def SetAutoscalerEnabled(self, enabled: bool,
                                   ttl_s: float = 30.0) -> dict:
        """An attached autoscaler flips lease semantics: locally
        infeasible requests queue (visible as demand) instead of failing
        (reference: infeasible tasks wait for the autoscaler). The flag
        is a TTL lease the autoscaler renews each reconcile round."""
        self.autoscaler_enabled_until = \
            (time.monotonic() + ttl_s) if enabled else 0.0
        return {"ok": True}

    def _cluster_view(self) -> Dict[str, dict]:
        return {
            n.node_id: {
                "addr": n.address,
                "alive": n.alive,
                "draining": n.draining,
                "total": dict(n.total_resources),
                "available": dict(n.available_resources),
            }
            for n in self.nodes.values()
        }

    async def GetClusterDemand(self) -> dict:
        """Autoscaler input (reference: autoscaler/v2 reads
        GcsAutoscalerStateManager): per-node availability, queued lease
        shapes, pending (unschedulable) actors, and idle times."""
        now = time.monotonic()
        pending_actors = [
            dict(a.resources)
            for a in self.actors.values()
            # lease_in_flight actors already appear in some raylet's
            # pending_shapes — counting both would double the demand
            if a.state == "PENDING" and not a.lease_in_flight
        ]
        return {
            "nodes": [
                {
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "draining": n.draining,
                    "is_head": n.is_head,
                    "total": dict(n.total_resources),
                    "available": dict(n.available_resources),
                    "pending_shapes": list(n.pending_shapes),
                    "num_leases": n.num_leases,
                    "idle_s": (now - n.idle_since)
                    if n.idle_since is not None else 0.0,
                    "labels": dict(n.labels),
                }
                for n in self.nodes.values()
            ],
            "pending_actors": pending_actors,
        }

    # ------------------------------------------------------------------
    # Graceful drain (reference: gcs_service.proto DrainNode with a
    # deadline + DRAIN_NODE_REASON_PREEMPTION; _private/drain.py has the
    # lifecycle). Planned node loss is a protocol, not a health-check
    # timeout: the node stops taking work, in-flight work finishes or
    # migrates, and only then is the node marked dead.
    # ------------------------------------------------------------------
    async def DrainNode(self, node_id: str, reason: str = "",
                        deadline_s: Optional[float] = None) -> dict:
        from ray_tpu._private import drain as drain_mod

        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return {"ok": False, "error": f"node {node_id[:12]} not alive"}
        if deadline_s is None:
            deadline_s = config.drain_deadline_default_s
        reason = reason or drain_mod.REASON_IDLE_TERMINATION
        # preempting one slice member preempts the whole slice: a TPU
        # pod slice is one ICI failure domain (SlicePlacementGroup /
        # JaxTrainer assume gang semantics), so the rest of the slice
        # drains with it rather than limping on and timing out later
        targets = [node]
        slice_id = node.labels.get("slice_id")
        if slice_id and reason == drain_mod.REASON_PREEMPTION:
            for n in self.nodes.values():
                if (n is not node and n.alive and not n.draining
                        and n.labels.get("slice_id") == slice_id):
                    targets.append(n)
        started = []
        for n in targets:
            if n.draining:
                continue
            self._start_drain(n, reason, deadline_s)
            started.append(n.node_id)
        return {"ok": True, "draining": started,
                "already_draining": node.draining and not started}

    def _enter_draining(self, node: NodeInfo, reason: str,
                        deadline_s: float) -> None:
        """Single entry point for the DRAINING state (used by the
        DrainNode orchestration, the heartbeat relearn after a GCS
        restart, and a raylet-initiated completion the GCS never saw
        start): sets the fields, bumps the node version, and publishes
        — every observer sees the same transition."""
        node.draining = True
        node.drain_reason = reason
        node.drain_started_at = time.monotonic()
        node.drain_deadline = node.drain_started_at + max(0.0, deadline_s)
        self._node_version += 1
        self._publish_and_wake(
            "node_state", node.node_id,
            {"alive": True, "draining": True, "reason": reason})

    def _start_drain(self, node: NodeInfo, reason: str,
                     deadline_s: float) -> None:
        from ray_tpu._private import drain as drain_mod

        self._enter_draining(node, reason, deadline_s)
        logger.info("draining node %s (%s, deadline %.1fs)",
                    node.node_id[:12], reason, deadline_s)
        self.cluster_events.add([{
            "type": drain_mod.EVENT_DRAIN_START,
            "ts": time.time(),
            "node_id": node.node_id,
            "reason": reason,
            "deadline_s": deadline_s,
        }])
        asyncio.ensure_future(self._drain_node_task(node, reason, deadline_s))

    def _drain_done_event(self, node_id: str) -> asyncio.Event:
        ev = self._drain_done_events.get(node_id)
        if ev is None:
            ev = self._drain_done_events[node_id] = asyncio.Event()
        return ev

    async def _drain_node_task(self, node: NodeInfo, reason: str,
                               deadline_s: float) -> None:
        """Orchestrate one node's drain: tell the raylet, migrate the
        actors, then wait for the raylet's completion (or the deadline)
        before declaring the node dead."""
        ev = self._drain_done_event(node.node_id)
        try:
            await self._raylet(node.node_id).acall(
                "Drain", reason=reason, deadline_s=deadline_s, timeout=10)
        except Exception as e:  # noqa: BLE001 — heartbeat replies carry
            # the drain instruction as a fallback; the watchdog bounds it
            logger.warning("Drain RPC to %s failed: %s",
                           node.node_id[:12], e)
        mig = asyncio.ensure_future(self._migrate_node_actors(node, reason))
        self._drain_migrations[node.node_id] = mig
        # wait for the raylet to confirm; the deadline plus a small
        # grace bounds the wait (the health watchdog is the backstop
        # when this task itself died with a restarted GCS)
        remaining = max(0.0, node.drain_deadline - time.monotonic())
        try:
            await asyncio.wait_for(
                ev.wait(), timeout=remaining + config.drain_watchdog_grace_s)
        except asyncio.TimeoutError:
            logger.warning("drain of %s hit its deadline without raylet "
                           "confirmation", node.node_id[:12])
        try:
            await asyncio.wait_for(mig, timeout=5.0)
        except (asyncio.TimeoutError, Exception):  # noqa: BLE001
            pass
        await self._finish_drain(node.node_id)

    async def _migrate_node_actors(self, node: NodeInfo,
                                   reason: str) -> None:
        """Gracefully restart every ALIVE actor off the draining node:
        the old instance first stops accepting and finishes its accepted
        tasks (worker DrainActor), then the normal failure path restarts
        it per max_restarts — with watchers woken by the published
        actor_state event, not a health-check timeout."""
        # only ALIVE actors need migration: a PENDING actor has run no
        # code — its scheduling loop re-picks on its own (the draining
        # node is excluded from _pick_node_for and rejects its lease),
        # and routing it through _handle_actor_failure would charge (or
        # at max_restarts=0, spend) a restart for a planned drain
        victims = [a for a in self.actors.values()
                   if a.node_id == node.node_id and a.state == "ALIVE"]
        if not victims:
            return
        budget = max(0.5, node.drain_deadline - time.monotonic() - 1.0)

        async def _one(actor: ActorInfo) -> None:
            if actor.worker_addr:
                try:
                    await asyncio.wait_for(
                        self._worker_client(tuple(actor.worker_addr)).acall(
                            "DrainActor", actor_id=actor.actor_id,
                            timeout_s=budget, timeout=budget + 5),
                        timeout=budget + 6)
                except Exception:  # noqa: BLE001 — worker already gone
                    pass
            a = self.actors.get(actor.actor_id)
            if a is not None and a.node_id == node.node_id \
                    and a.state == "ALIVE":
                await self._handle_actor_failure(
                    a, f"node {node.node_id[:12]} draining ({reason})")

        await asyncio.gather(*(_one(a) for a in victims),
                             return_exceptions=True)
        logger.info("migrated %d actor(s) off draining node %s",
                    len(victims), node.node_id[:12])

    async def NodeDrainComplete(self, node_id: str,
                                moved_objects: Optional[dict] = None) -> dict:
        """Raylet-side drain finished: record where it pushed its
        primary object copies, wait for the actor migration, and mark
        the node dead. The raylet blocks on this reply before killing
        its workers, so migration RPCs to them cannot race the exit."""
        if moved_objects:
            self._record_moved_objects(moved_objects)
        mig = self._drain_migrations.get(node_id)
        if mig is not None and not mig.done():
            try:
                await asyncio.wait_for(asyncio.shield(mig), timeout=30)
            except (asyncio.TimeoutError, Exception):  # noqa: BLE001
                pass
        node = self.nodes.get(node_id)
        if node is not None and node.alive and not node.draining:
            # raylet-initiated drain that finished inside one heartbeat
            # period (we never saw DRAINING): the raylet is exiting
            # regardless — run the completion path so the node is
            # cleanly dead instead of waiting out the health checker
            self._enter_draining(node, node.drain_reason, 0.0)
        if node is not None and node.draining:
            self._drain_done_event(node_id).set()
            await self._finish_drain(node_id)
        return {"ok": True}

    _MOVED_OBJECTS_MAX = 20_000

    def _record_moved_objects(self, moved: dict) -> None:
        """Bounded oid_bin -> node_id directory of primary copies pushed
        off drained nodes; owners consult it when a pull from the
        recorded node fails (_pull_remote_object fallback)."""
        table = self.moved_objects
        for oid_bin, nid in moved.items():
            table[bytes(oid_bin)] = nid
            table.move_to_end(bytes(oid_bin))
        while len(table) > self._MOVED_OBJECTS_MAX:
            table.popitem(last=False)

    async def LookupObjectLocations(self, object_id_bins: List[bytes]) -> dict:
        table = self.moved_objects
        return {
            bytes(b): table[bytes(b)]
            for b in object_id_bins if bytes(b) in table
        }

    async def _finish_drain(self, node_id: str) -> None:
        from ray_tpu._private import drain as drain_mod

        node = self.nodes.get(node_id)
        if node is None or not node.draining:
            return
        node.draining = False
        node.alive = False
        node.drain_deadline = 0.0
        self._node_version += 1
        duration = time.monotonic() - (node.drain_started_at
                                       or time.monotonic())
        logger.info("drain of node %s complete (%.1fs)",
                    node_id[:12], duration)
        self.cluster_events.add([{
            "type": drain_mod.EVENT_DRAIN_COMPLETE,
            "ts": time.time(),
            "node_id": node_id,
            "reason": node.drain_reason,
            "duration_s": round(duration, 3),
        }])
        self._publish_and_wake(
            "node_state", node_id, {"alive": False, "drained": True})
        self._drain_migrations.pop(node_id, None)
        self._drain_done_events.pop(node_id, None)
        # drop the cached raylet client — the daemon is exiting
        c = self._raylet_clients.pop(node_id, None)
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        # any actor the migration missed fails over through the normal
        # node-death path (idempotent for already-RESTARTING actors)
        await self._on_node_death(node_id)

    async def GetAllNodeInfo(self) -> List[dict]:
        return [
            {
                "NodeID": n.node_id,
                "Alive": n.alive,
                "Draining": n.draining,
                "DrainReason": n.drain_reason if n.draining else "",
                "NodeManagerAddress": n.address[0],
                "NodeManagerPort": n.address[1],
                "ObjectStoreSocketName": n.store_socket,
                "Resources": dict(n.total_resources),
                "AvailableResources": dict(n.available_resources),
                "IsHead": n.is_head,
                "Labels": dict(n.labels),
                "AgentPort": n.agent_port,
            }
            for n in self.nodes.values()
        ]

    async def GetClusterResources(self) -> Dict[str, Dict[str, float]]:
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive or n.draining:
                continue
            for k, v in n.total_resources.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.available_resources.items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    async def _health_check_loop(self) -> None:
        period = config.gcs_health_check_period_ms / 1000.0
        threshold = (
            config.gcs_health_check_period_ms
            * config.gcs_health_check_failure_threshold
            / 1000.0
        )
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            self._sample_control_plane_counters()
            for node in list(self.nodes.values()):
                if node.alive and node.draining and \
                        now > node.drain_deadline \
                        + config.drain_watchdog_grace_s:
                    # drain watchdog: past deadline + grace a DRAINING
                    # node is force-completed (the raylet died mid-drain,
                    # or a restarted GCS lost the orchestration task) —
                    # no node sits DRAINING forever
                    logger.warning(
                        "node %s stuck DRAINING past its deadline; "
                        "force-completing", node.node_id[:12])
                    self._debug_dump_fanout(
                        "drain_deadline_expired", node_id=node.node_id,
                        reason=node.drain_reason)
                    await self._finish_drain(node.node_id)
                    continue
                if node.alive and now - node.last_heartbeat > threshold:
                    logger.warning("node %s missed heartbeats; marking dead", node.node_id[:12])
                    if node.draining:
                        # a DRAINING node that stops heartbeating died
                        # mid-drain: run the full completion path so the
                        # NODE_DRAIN_COMPLETE event fires and the drain
                        # bookkeeping (done events, migration task,
                        # cached raylet client) is cleaned up
                        await self._finish_drain(node.node_id)
                        continue
                    node.alive = False
                    self._node_version += 1
                    self._publish_and_wake(
                        "node_state", node.node_id, {"alive": False}
                    )
                    await self._on_node_death(node.node_id)

    async def _on_node_death(self, node_id: str) -> None:
        # actors on that node die / restart
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in ("ALIVE", "PENDING"):
                await self._handle_actor_failure(actor, f"node {node_id[:12]} died")

    # ------------------------------------------------------------------
    # Job management
    # ------------------------------------------------------------------
    async def RegisterJob(self, driver_addr: Tuple[str, int], metadata: Optional[dict] = None) -> dict:
        self._job_counter += 1
        job_id_int = self._job_counter
        from ray_tpu._private.ids import JobID

        job_id = JobID.from_int(job_id_int).hex()
        self.jobs[job_id] = {
            "job_id": job_id,
            "driver_addr": tuple(driver_addr),
            "start_time": time.time(),
            "state": "RUNNING",
            "metadata": metadata or {},
        }
        self._log("job_counter", self._job_counter)
        self._log("job", job_id, self.jobs[job_id])
        return {"job_id_int": job_id_int, "job_id": job_id}

    async def MarkJobFinished(self, job_id: str) -> dict:
        if job_id in self.jobs:
            self.jobs[job_id]["state"] = "FINISHED"
            self.jobs[job_id]["end_time"] = time.time()
            self._log("job", job_id, self.jobs[job_id])
        # non-detached actors owned by the job die with it
        for actor in list(self.actors.values()):
            if actor.job_id == job_id and not actor.detached and actor.state != "DEAD":
                await self._kill_actor_impl(actor, "job finished")
        return {"ok": True}

    async def ListJobs(self) -> List[dict]:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # KV (function table, runtime env, cluster metadata)
    # ------------------------------------------------------------------
    async def KVPut(self, ns: str, key: str, value: bytes, overwrite: bool = True) -> dict:
        table = self.kv.setdefault(ns, {})
        if not overwrite and key in table:
            return {"added": False}
        table[key] = value
        self._log_kv(ns, key, value)
        return {"added": True}

    async def KVGet(self, ns: str, key: str) -> Optional[bytes]:
        return self.kv.get(ns, {}).get(key)

    async def KVDel(self, ns: str, key: str) -> dict:
        self.kv.get(ns, {}).pop(key, None)
        if ns in self._BLOB_NAMESPACES and self.storage_path:
            try:
                os.unlink(os.path.join(self._blob_dir(), ns + "." + key))
            except OSError:
                pass
        self._log("kv_del", ns, key)
        return {"ok": True}

    async def KVKeys(self, ns: str, prefix: str = "") -> List[str]:
        return [k for k in self.kv.get(ns, {}) if k.startswith(prefix)]

    async def KVExists(self, ns: str, key: str) -> bool:
        return key in self.kv.get(ns, {})

    # ------------------------------------------------------------------
    # Actor management
    # ------------------------------------------------------------------
    async def RegisterActor(
        self,
        actor_id: str,
        job_id: str,
        serialized_spec: bytes,
        name: Optional[str],
        namespace: str,
        max_restarts: int,
        resources: Dict[str, float],
        owner_addr: Tuple[str, int],
        detached: bool = False,
        get_if_exists: bool = False,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        cpu_scheduling_only: bool = False,
        runtime_env_hash: str = "",
        scheduling_kind: str = "DEFAULT",
        affinity_node_id: Optional[str] = None,
        strategy_soft: bool = False,
        node_labels: Optional[Dict[str, str]] = None,
    ) -> dict:
        # idempotent retry: a caller re-sending after a lost reply (GCS
        # crash post-persist, or chaos response drop) must not create a
        # second instance or see a spurious name conflict
        if actor_id in self.actors:
            return {"actor_id": actor_id, "existing": True}
        if name:
            existing = self.named_actors.get((namespace, name))
            if existing is not None and existing != actor_id:
                ex = self.actors.get(existing)
                if ex is not None and ex.state != "DEAD":
                    if get_if_exists:
                        return {"actor_id": existing, "existing": True}
                    return {"error": f"Actor with name '{name}' already exists"}
        actor = ActorInfo(
            actor_id=actor_id,
            job_id=job_id,
            name=name,
            namespace=namespace,
            state="PENDING",
            serialized_spec=serialized_spec,
            owner_addr=tuple(owner_addr),
            max_restarts=max_restarts,
            resources=dict(resources),
            detached=detached,
            pg_id=pg_id,
            bundle_index=bundle_index,
            cpu_scheduling_only=cpu_scheduling_only,
            runtime_env_hash=runtime_env_hash,
            scheduling_kind=scheduling_kind,
            affinity_node_id=affinity_node_id,
            strategy_soft=strategy_soft,
            node_labels=dict(node_labels) if node_labels else None,
        )
        self.actors[actor_id] = actor
        self._log("actor", actor)
        obs_timeline.mark_actor(actor_id, "registered", job_id=job_id)
        if name:
            self.named_actors[(namespace, name)] = actor_id
            self._log("named", namespace, name, actor_id)
        asyncio.ensure_future(self._schedule_actor(actor))
        return {"actor_id": actor_id, "existing": False}

    def _pick_node_for(self, resources: Dict[str, float], pg: Optional[PlacementGroupInfo], bundle_index: int,
                       actor: Optional[ActorInfo] = None) -> Optional[str]:
        """GCS-side actor scheduling (reference: GcsActorScheduler
        gcs_actor_scheduler.h:104 — uses cluster resource view); honors
        the actor's node-affinity / node-label strategy."""
        if pg is not None:
            if bundle_index >= 0:
                return pg.bundle_nodes.get(bundle_index)
            # any bundle's node with room
            for idx, nid in pg.bundle_nodes.items():
                node = self.nodes.get(nid)
                if node and node.alive and not node.draining:
                    return nid
            return None

        def _matches(n: NodeInfo) -> bool:
            if actor is None:
                return True
            if actor.scheduling_kind == "NODE_AFFINITY":
                return n.node_id == actor.affinity_node_id
            if actor.scheduling_kind == "NODE_LABEL":
                return all(n.labels.get(k) == v
                           for k, v in (actor.node_labels or {}).items())
            return True

        alive = [n for n in self.nodes.values()
                 if n.alive and not n.draining]
        allowed = [n for n in alive if _matches(n)]
        if actor is not None and actor.strategy_soft:
            # soft: fall back when nothing matches OR the matches can
            # never fit the request (total resources too small)
            fittable = [
                n for n in allowed
                if all(n.total_resources.get(k, 0.0) >= v
                       for k, v in resources.items())
            ]
            if not fittable:
                allowed = alive
        candidates = []
        for n in allowed:
            if all(n.available_resources.get(k, 0.0) >= v for k, v in resources.items()):
                # least-loaded first: fewest live actors already placed there
                load = sum(1 for a in self.actors.values()
                           if a.node_id == n.node_id and a.state != "DEAD")
                candidates.append((load, n.node_id))
        if not candidates:
            # fall back: any ALLOWED node whose *total* resources fit
            # (may queue behind current occupants)
            for n in allowed:
                if all(n.total_resources.get(k, 0.0) >= v for k, v in resources.items()):
                    return n.node_id
            return None
        candidates.sort()
        return candidates[0][1]

    def _creation_gate(self, node_id: str):
        """Admission control for actor creation (reference:
        GcsActorScheduler bounds in-flight leases per node). A burst of
        thousands of RegisterActor calls must NOT run thousands of
        lease+spawn+CreateActor pipelines concurrently: on a host whose
        CPU count is far below the burst size, every stage of every
        pipeline times out against the others and creation collapses
        (observed: 624/2000 actors never ALIVE on the 1-CPU CI box).
        Bounded concurrency turns the herd into a steady pipeline at
        identical throughput — the stages are CPU-bound anyway.

        One gate PER TARGET NODE (`actor_creation_concurrency` each):
        total in-flight creations scale with the cluster, and one slow
        node's pipeline backlog cannot stall placements elsewhere."""
        gate = self._actor_create_gates.get(node_id)
        if gate is None:
            gate = self._actor_create_gates[node_id] = asyncio.Semaphore(
                max(1, config.actor_creation_concurrency))
        return gate

    def _maybe_prestart_workers(self) -> None:
        """Overlap worker bring-up with the creation pipeline: when a
        burst of PENDING actors is queued, tell each node's raylet to
        prefork workers NOW (zygote spawns run while earlier creations
        hold the gate), so the lease stage of later pipelines finds
        registered idle workers instead of paying a cold spawn inside
        its gate slot (reference: WorkerPool::PrestartWorkers,
        worker_pool.h:280). Throttled; oneway — never blocks scheduling."""
        now = time.monotonic()
        if now - self._last_prestart < 0.25:
            return
        pending = sum(1 for a in self.actors.values()
                      if a.state == "PENDING")
        if pending < 2:
            return
        self._last_prestart = now
        alive = [n for n in self.nodes.values()
                 if n.alive and not n.draining]
        if not alive:
            return
        per_node = max(1, min(config.actor_creation_concurrency,
                              (pending + len(alive) - 1) // len(alive)))
        for n in alive:
            try:
                self._raylet(n.node_id).call_oneway(
                    "PrestartWorkers", count=per_node)
            except Exception:  # noqa: BLE001 — advisory
                pass

    async def _schedule_actor(self, actor: ActorInfo) -> None:
        """Lease a worker for the actor and push its creation task
        (reference: GcsActorScheduler + SchedulePendingActors
        gcs_actor_manager.cc:1721). The creation gate bounds only the
        lease+CreateActor attempt — an actor merely WAITING for
        placeable resources holds no slot, so unplaceable actors can't
        starve the pipeline."""
        deadline = time.monotonic() + config.actor_schedule_timeout_s
        while time.monotonic() < deadline:
            if actor.state == "DEAD":
                return
            # hard affinity to a node id that is registered-but-dead can
            # never succeed (node ids are never reused) — fail fast with
            # a precise cause instead of spinning out the 300s deadline.
            # (Hard LABELS keep waiting: a matching node may be added,
            # e.g. by the autoscaler.)
            if (actor.scheduling_kind == "NODE_AFFINITY"
                    and not actor.strategy_soft):
                target = self.nodes.get(actor.affinity_node_id)
                if target is not None and not target.alive:
                    actor.state = "DEAD"
                    actor.death_cause = (
                        f"node {actor.affinity_node_id[:12]} is dead "
                        f"(NodeAffinity soft=False)")
                    actor.version += 1
                    self._notify_actor(actor.actor_id)
                    return
            pg = self.placement_groups.get(actor.pg_id) if actor.pg_id else None
            node_id = self._pick_node_for(actor.resources, pg,
                                          actor.bundle_index, actor=actor)
            if node_id is None:
                await asyncio.sleep(0.2)
                continue
            self._maybe_prestart_workers()
            gate_wait_from = time.monotonic()
            async with self._creation_gate(node_id):
                # The schedule deadline must budget CREATION time, not
                # time spent QUEUED behind other creations at the gate:
                # in a large burst with slow __init__, tail actors sit at
                # the gate for most of the 300s window and were marked
                # DEAD on their first transient retry. Credit the queue
                # wait back (reference: the per-node in-flight lease
                # bound applies before the scheduling timer starts).
                deadline += time.monotonic() - gate_wait_from
                if actor.state == "DEAD":  # killed while queued at gate
                    return
                outcome = await self._try_create_once(actor, node_id)
            if outcome is None:
                return
            await asyncio.sleep(outcome)
        actor.state = "DEAD"
        if actor.scheduling_kind in ("NODE_AFFINITY", "NODE_LABEL") \
                and not actor.strategy_soft:
            actor.death_cause = (
                f"scheduling timed out: no node satisfied the hard "
                f"{actor.scheduling_kind} constraint "
                f"(node_id={actor.affinity_node_id!r}, "
                f"labels={actor.node_labels!r})")
        else:
            actor.death_cause = "scheduling timed out (insufficient resources?)"
        actor.version += 1
        self._notify_actor(actor.actor_id)

    async def _try_create_once(self, actor: ActorInfo,
                               node_id: str) -> Optional[float]:
        """One gated lease+CreateActor attempt. Returns None when the
        actor reached a terminal state (ALIVE or DEAD), else the retry
        delay for the caller's loop."""
        try:
            obs_timeline.mark_actor(actor.actor_id, "scheduled",
                                    job_id=actor.job_id, node_id=node_id)
            raylet = self._raylet(node_id)
            actor.lease_in_flight = True
            try:
                reply = await raylet.acall(
                    "RequestWorkerLease",
                    resources=actor.resources,
                    scheduling_class=("actor", actor.actor_id),
                    job_id=actor.job_id,
                    for_actor=actor.actor_id,
                    pg_id=actor.pg_id,
                    bundle_index=actor.bundle_index,
                    lease_timeout=50.0,
                    release_cpu_after_grant=actor.cpu_scheduling_only,
                    runtime_env_hash=actor.runtime_env_hash,
                    timeout=60,
                )
            finally:
                actor.lease_in_flight = False
        except Exception as e:  # noqa: BLE001
            logger.warning("actor %s lease request to %s failed: %s", actor.actor_id[:12], node_id[:12], e)
            return 0.5
        if not reply.get("granted"):
            return 0.2
        obs_timeline.mark_actor(actor.actor_id, "lease_granted",
                                job_id=actor.job_id, node_id=node_id)
        worker_addr = tuple(reply["worker_addr"])
        try:
            worker = self._worker_client(worker_addr)
            creation_reply = await worker.acall(
                "CreateActor",
                actor_id=actor.actor_id,
                serialized_spec=actor.serialized_spec,
                # actor __init__ is user code (may cold-import jax,
                # build models); the generic RPC timeout would abort
                # + re-lease in a loop, never letting init finish
                timeout=config.actor_creation_timeout_s,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("actor %s creation push failed: %s", actor.actor_id[:12], e)
            # the worker may still be running __init__ — return the lease
            # with worker_dead=True (kills the worker) so the retry can't
            # produce a second live instance and the lease isn't leaked
            try:
                await self._raylet(node_id).acall(
                    "ReturnWorkerLease", lease_id=reply["lease_id"], worker_dead=True
                )
            except Exception:
                pass
            return 0.5
        if creation_reply.get("ok"):
            actor.state = "ALIVE"
            actor.worker_addr = worker_addr
            actor.node_id = node_id
            actor.worker_id = reply.get("worker_id")
            actor.version += 1
            obs_timeline.mark_actor(actor.actor_id, "alive",
                                    job_id=actor.job_id, node_id=node_id)
            self._notify_actor(actor.actor_id)
            logger.info("actor %s alive on %s", actor.actor_id[:12], node_id[:12])
            return None
        # creation raised in user __init__ — actor is dead
        actor.state = "DEAD"
        actor.death_cause = creation_reply.get("error", "creation failed")
        actor.version += 1
        self._notify_actor(actor.actor_id)
        try:
            await self._raylet(node_id).acall(
                "ReturnWorkerLease", lease_id=reply["lease_id"], worker_dead=False
            )
        except Exception:
            pass
        return None

    def _notify_actor(self, actor_id: str) -> None:
        evt = self._actor_events.get(actor_id)
        if evt is not None:
            evt.set()
            self._actor_events[actor_id] = asyncio.Event()
        a = self.actors.get(actor_id)
        self._publish_and_wake(
            "actor_state", actor_id,
            # the event carries enough to RESOLVE the actor (state +
            # address): subscribers' warm path needs no GetActorInfo
            # round-trip after the wake
            {"state": a.state, "version": a.version,
             "worker_addr": tuple(a.worker_addr) if a.worker_addr else None,
             "death_cause": a.death_cause} if a else None,
        )
        if a is not None:
            self._log_actor_state(a)  # every state change is durable;
            # slim record — the full spec was logged at registration

    async def GetActorInfo(self, actor_id: str) -> Optional[dict]:
        a = self.actors.get(actor_id)
        if a is None:
            return None
        return {
            "actor_id": a.actor_id,
            "state": a.state,
            "worker_addr": a.worker_addr,
            "node_id": a.node_id,
            "name": a.name,
            "num_restarts": a.num_restarts,
            "death_cause": a.death_cause,
            "version": a.version,
        }

    async def WaitActorUpdate(self, actor_id: str, from_version: int, timeout_s: float = 10.0) -> Optional[dict]:
        """Long-poll for actor state changes (reference: pubsub actor channel)."""
        a = self.actors.get(actor_id)
        if a is None:
            return None
        if a.version > from_version:
            return await self.GetActorInfo(actor_id)
        evt = self._actor_events.setdefault(actor_id, asyncio.Event())
        try:
            await asyncio.wait_for(evt.wait(), timeout=timeout_s)
        except asyncio.TimeoutError:
            pass
        return await self.GetActorInfo(actor_id)

    async def GetActorByName(self, name: str, namespace: str) -> Optional[str]:
        aid = self.named_actors.get((namespace, name))
        if aid is not None:
            a = self.actors.get(aid)
            if a is not None and a.state != "DEAD":
                return aid
        return None

    async def ListActors(self) -> List[dict]:
        return [await self.GetActorInfo(aid) for aid in list(self.actors)]

    async def ListPlacementGroups(self) -> List[dict]:
        return [
            {
                "placement_group_id": pg.pg_id,
                "name": pg.name,
                "state": pg.state,
                "strategy": pg.strategy,
                "bundles": pg.bundles,
                "bundle_nodes": dict(pg.bundle_nodes),
            }
            for pg in self.placement_groups.values()
        ]

    async def ReportActorFault(self, actor_id: str, worker_addr: Tuple[str, int], error: str) -> dict:
        """Called by a caller that failed to reach the actor's worker."""
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False}
        if actor.state == "ALIVE" and actor.worker_addr == tuple(worker_addr):
            await self._handle_actor_failure(actor, error)
        return {"ok": True}

    async def NotifyWorkerDeath(self, node_id: str, worker_id: str, worker_addr: Tuple[str, int]) -> dict:
        """Raylet reports a worker process exit."""
        for actor in list(self.actors.values()):
            if actor.state == "ALIVE" and actor.worker_addr == tuple(worker_addr):
                await self._handle_actor_failure(actor, f"worker process died on {node_id[:12]}")
        return {"ok": True}

    async def _handle_actor_failure(self, actor: ActorInfo, cause: str) -> None:
        # actor restarts/deaths are first-class bus events (the GCS is
        # the aggregator, so it appends directly — no RPC to itself)
        self.cluster_events.add([{
            "type": "actor_restart",
            "ts": time.time(),
            "actor_id": actor.actor_id,
            "job_id": actor.job_id,
            "num_restarts": actor.num_restarts,
            "will_restart": actor.num_restarts < actor.max_restarts
            or actor.max_restarts == -1,
            "cause": cause,
        }])
        if actor.num_restarts < actor.max_restarts or actor.max_restarts == -1:
            actor.num_restarts += 1
            actor.state = "RESTARTING"
            actor.worker_addr = None
            # recorded for RESTARTING too: callers use it to tell a
            # PLANNED restart (node drain — old instance finished its
            # accepted work, safe to resend) from a crash
            actor.death_cause = cause
            actor.version += 1
            self._notify_actor(actor.actor_id)
            logger.info("actor %s restarting (%d/%s): %s", actor.actor_id[:12], actor.num_restarts, actor.max_restarts, cause)
            asyncio.ensure_future(self._schedule_actor(actor))
        else:
            actor.state = "DEAD"
            actor.death_cause = cause
            actor.worker_addr = None
            actor.version += 1
            self._notify_actor(actor.actor_id)
            # restarts exhausted: the black box gets persisted while the
            # failure context is still in everyone's rings
            self._debug_dump_fanout(
                "actor_restarts_exhausted", actor_id=actor.actor_id,
                job_id=actor.job_id, cause=cause)

    async def KillActor(self, actor_id: str, no_restart: bool = True) -> dict:
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False}
        await self._kill_actor_impl(actor, "ray_tpu.kill()", no_restart=no_restart)
        return {"ok": True}

    async def _kill_actor_impl(self, actor: ActorInfo, cause: str, no_restart: bool = True) -> None:
        worker_addr = actor.worker_addr
        if no_restart:
            actor.state = "DEAD"
            actor.death_cause = cause
            actor.version += 1
            if actor.name:
                # durable un-delete guard: without this record a crash
                # before the next compaction would resurrect the
                # name→DEAD-actor mapping on WAL replay
                if self.named_actors.pop(
                        (actor.namespace, actor.name), None) is not None:
                    self._log("named_del", actor.namespace, actor.name)
            self._notify_actor(actor.actor_id)
        if worker_addr:
            try:
                worker = self._worker_client(tuple(worker_addr))
                await worker.acall("KillActor", actor_id=actor.actor_id, timeout=5)
            except Exception:
                pass
        if not no_restart:
            a = self.actors.get(actor.actor_id)
            if a:
                await self._handle_actor_failure(a, cause)

    # ------------------------------------------------------------------
    # Placement groups (2PC prepare/commit — node_manager.proto:514-519)
    # ------------------------------------------------------------------
    async def CreatePlacementGroup(
        self,
        pg_id: str,
        name: str,
        bundles: List[Dict[str, float]],
        strategy: str,
        creator_job: str = "",
    ) -> dict:
        if pg_id in self.placement_groups:
            return {"pg_id": pg_id}
        pg = PlacementGroupInfo(
            pg_id=pg_id,
            name=name,
            strategy=strategy,
            bundles=[dict(b) for b in bundles],
            state="PENDING",
            creator_job=creator_job,
        )
        self.placement_groups[pg_id] = pg
        self._log("pg", pg)
        asyncio.ensure_future(self._schedule_pg(pg))
        return {"pg_id": pg_id}

    def _plan_bundles(self, pg: PlacementGroupInfo) -> Optional[Dict[int, str]]:
        """Bin-pack bundles onto alive nodes per strategy (reference:
        bundle_scheduling_policy.h bundle pack/spread)."""
        alive = [n for n in self.nodes.values()
                 if n.alive and not n.draining]
        if not alive:
            return None
        # simulate available resources
        sim = {n.node_id: dict(n.available_resources) for n in alive}

        def fits(nid: str, b: Dict[str, float]) -> bool:
            return all(sim[nid].get(k, 0.0) >= v for k, v in b.items())

        def take(nid: str, b: Dict[str, float]) -> None:
            for k, v in b.items():
                sim[nid][k] = sim[nid].get(k, 0.0) - v

        plan: Dict[int, str] = {}
        order = list(range(len(pg.bundles)))
        if pg.strategy in ("PACK", "STRICT_PACK"):
            node_ids = [n.node_id for n in alive]
            for idx in order:
                b = pg.bundles[idx]
                placed = False
                # prefer nodes already used
                used = list(dict.fromkeys(plan.values()))
                for nid in used + [n for n in node_ids if n not in used]:
                    if fits(nid, b):
                        take(nid, b)
                        plan[idx] = nid
                        placed = True
                        break
                if not placed:
                    return None
            if pg.strategy == "STRICT_PACK" and len(set(plan.values())) > 1:
                return None
        else:  # SPREAD / STRICT_SPREAD
            node_ids = [n.node_id for n in alive]
            i = 0
            for idx in order:
                b = pg.bundles[idx]
                placed = False
                for attempt in range(len(node_ids)):
                    nid = node_ids[(i + attempt) % len(node_ids)]
                    if pg.strategy == "STRICT_SPREAD" and nid in plan.values():
                        continue
                    if fits(nid, b):
                        take(nid, b)
                        plan[idx] = nid
                        i += 1
                        placed = True
                        break
                if not placed:
                    return None
        return plan

    async def _schedule_pg(self, pg: PlacementGroupInfo) -> None:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline and pg.state == "PENDING":
            plan = self._plan_bundles(pg)
            if plan is None:
                await asyncio.sleep(0.2)
                continue
            # 2PC: PREPARE on all nodes, then COMMIT (reference:
            # PrepareBundleResources / CommitBundleResources)
            prepared: List[Tuple[str, int]] = []
            ok = True
            for idx, nid in plan.items():
                try:
                    r = await self._raylet(nid).acall(
                        "PrepareBundle",
                        pg_id=pg.pg_id,
                        bundle_index=idx,
                        resources=pg.bundles[idx],
                    )
                    if not r.get("ok"):
                        ok = False
                        break
                    prepared.append((nid, idx))
                except Exception:
                    ok = False
                    break
            if not ok:
                for nid, idx in prepared:
                    try:
                        await self._raylet(nid).acall("CancelBundle", pg_id=pg.pg_id, bundle_index=idx)
                    except Exception:
                        pass
                await asyncio.sleep(0.2)
                continue
            for idx, nid in plan.items():
                await self._raylet(nid).acall("CommitBundle", pg_id=pg.pg_id, bundle_index=idx)
            pg.bundle_nodes = plan
            pg.state = "CREATED"
            self._log("pg", pg)
            logger.info("placement group %s created: %s", pg.pg_id[:12], {i: n[:8] for i, n in plan.items()})
            return
        if pg.state == "PENDING":
            pg.state = "INFEASIBLE"

    async def GetPlacementGroup(self, pg_id: str) -> Optional[dict]:
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return None
        return {
            "pg_id": pg.pg_id,
            "name": pg.name,
            "state": pg.state,
            "strategy": pg.strategy,
            "bundles": pg.bundles,
            "bundle_nodes": dict(pg.bundle_nodes),
        }

    async def RemovePlacementGroup(self, pg_id: str) -> dict:
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return {"ok": False}
        for idx, nid in pg.bundle_nodes.items():
            try:
                await self._raylet(nid).acall("ReleaseBundle", pg_id=pg_id, bundle_index=idx)
            except Exception:
                pass
        pg.state = "REMOVED"
        pg.bundle_nodes = {}
        self._log("pg", pg)  # after the clear: replay must not
        # resurrect stale bundle->node assignments
        return {"ok": True}

    # ------------------------------------------------------------------
    # Observability: metrics aggregation + Prometheus endpoint, task
    # events, log buffering (reference: src/ray/stats/metric.h:104,
    # GcsTaskManager task-event history, _private/log_monitor.py)
    # ------------------------------------------------------------------
    async def ReportMetrics(self, producer: str, metrics: List[dict]) -> dict:
        now = time.monotonic()
        self.metrics_by_producer[producer] = (metrics, now)
        # evict dead producers here too (not only at scrape time) so the
        # table stays bounded on clusters nobody scrapes
        if len(self.metrics_by_producer) % 16 == 0:
            self.metrics_by_producer = {
                p: (m, ts) for p, (m, ts) in self.metrics_by_producer.items()
                if now - ts < 30.0
            }
        return {"ok": True}

    async def ReportTaskEvents(self, events: List[dict]) -> dict:
        self.task_events.extend(events)
        return {"ok": True}

    async def ListTaskEvents(self, job_id: Optional[str] = None,
                             limit: int = 1000) -> List[dict]:
        out = [
            e for e in self.task_events
            if job_id is None or e.get("job_id") == job_id
        ]
        return out[-limit:]

    # -- event bus + tracing (observability/: workers push typed-event
    # batches; spans are indexed per job for GetTrace) ------------------
    async def ReportClusterEvents(self, events: List[dict],
                                  clock: Optional[dict] = None) -> dict:
        self.cluster_events.add(events, clock=clock)
        return {"ok": True}

    # -- lifecycle timelines (observability/timeline.py analysis over
    # the aggregator's actor/task phase marks) --------------------------
    async def ActorTimeline(self, actor_id: str) -> dict:
        return self.cluster_events.actor_timeline(actor_id)

    async def LifecycleSummary(self, job_id: Optional[str] = None,
                               wall_s: Optional[float] = None,
                               etype: str = "actor_lifecycle") -> dict:
        return self.cluster_events.lifecycle_summary(
            job_id=job_id, wall_s=wall_s, etype=etype)

    # -- flight-recorder dumps (observability/dump.py) ------------------
    def _sample_control_plane_counters(self) -> None:
        """Counter-track samples for debug dumps: queue depths the
        postmortem trace shows next to the event timeline."""
        from ray_tpu.observability import dump as obs_dump

        pending = sum(1 for a in self.actors.values()
                      if a.state in ("PENDING", "RESTARTING"))
        obs_dump.counter_sample("gcs_pending_actors", pending)
        obs_dump.counter_sample(
            "gcs_alive_nodes",
            sum(1 for n in self.nodes.values() if n.alive))

    def _debug_dump_fanout(self, reason: str, **info: Any) -> None:
        """Persist the GCS's own black box and ask every reachable
        process (raylets, job drivers, a capped set of actor workers)
        to do the same — fire-and-forget, rate-limited."""
        from ray_tpu.observability import dump as obs_dump

        now = time.monotonic()
        if now - self._last_fanout_dump < 5.0:
            obs_dump.dump_now(reason, extra=info or None)
            return
        self._last_fanout_dump = now
        obs_dump.dump_now(reason, extra=dict(
            info, gcs={"actors": len(self.actors),
                       "pending_actors": sum(
                           1 for a in self.actors.values()
                           if a.state in ("PENDING", "RESTARTING")),
                       "nodes": len(self.nodes)}))
        targets: List[Tuple[str, Any]] = []
        for node in self.nodes.values():
            if node.alive:
                try:
                    targets.append((f"raylet:{node.node_id[:12]}",
                                    self._raylet(node.node_id)))
                except Exception:  # noqa: BLE001 — unreachable raylet
                    pass
        for job in self.jobs.values():
            if job.get("state") == "RUNNING" and job.get("driver_addr"):
                try:
                    targets.append((f"driver:{job['job_id'][:12]}",
                                    self._worker_client(
                                        tuple(job["driver_addr"]))))
                except Exception:  # noqa: BLE001
                    pass
        seen_addrs = set()
        for actor in self.actors.values():
            if len(seen_addrs) >= 32:
                break  # cap the worker fan-out; rings are per PROCESS
            if actor.state == "ALIVE" and actor.worker_addr and \
                    tuple(actor.worker_addr) not in seen_addrs:
                seen_addrs.add(tuple(actor.worker_addr))
                try:
                    targets.append((f"worker:{actor.actor_id[:12]}",
                                    self._worker_client(
                                        tuple(actor.worker_addr))))
                except Exception:  # noqa: BLE001
                    pass

        async def _fan() -> None:
            for name, client in targets:
                try:
                    await client.acall("DebugDump", reason=reason,
                                       info=info, timeout=5)
                except Exception:  # noqa: BLE001 — best-effort postmortem
                    logger.debug("debug dump to %s failed", name)

        asyncio.ensure_future(_fan())

    async def TriggerDebugDump(self, reason: str,
                               info: Optional[dict] = None) -> dict:
        """Any process that hit a typed failure asks the GCS to fan the
        cluster-wide dump out (see observability/dump.py)."""
        self._debug_dump_fanout(reason, **(info or {}))
        return {"ok": True}

    async def ListClusterEvents(self, etype: Optional[str] = None,
                                job_id: Optional[str] = None,
                                limit: int = 1000) -> List[dict]:
        return self.cluster_events.list_events(etype=etype, job_id=job_id,
                                               limit=limit)

    async def GetTrace(self, job_id: str) -> dict:
        return self.cluster_events.get_trace(job_id)

    async def ReportNodeStats(self, node_id: str, stats: dict) -> dict:
        """Per-node reporter samples from the dashboard agents
        (reference: dashboard/agent.py reporter module)."""
        self.cluster_events.set_node_stats(node_id, stats)
        return {"ok": True}

    async def ListNodeStats(self) -> List[dict]:
        return self.cluster_events.list_node_stats()

    async def PublishLogs(self, node_id: str, worker_id: str,
                          lines: List[str]) -> dict:
        for ln in lines:
            self._log_seq += 1
            self.log_buffer.append((self._log_seq, node_id, worker_id, ln))
        return {"ok": True}

    async def GetLogs(self, after_seq: int = 0, limit: int = 1000) -> dict:
        """Worker log lines are cluster-wide (not scoped per job — worker
        processes serve any job; the reference's per-job log routing is a
        deliberate simplification here)."""
        lines = [e for e in self.log_buffer if e[0] > after_seq][:limit]
        next_seq = lines[-1][0] if lines else after_seq
        return {"lines": lines, "next_seq": next_seq, "latest_seq": self._log_seq}

    async def GetMetricsEndpoint(self) -> dict:
        return {"host": "127.0.0.1", "port": self.metrics_http_port}

    # ------------------------------------------------------------------
    # Pubsub (reference: src/ray/pubsub/ — long-poll Publisher
    # publisher.h:357 / Subscriber subscriber.h:215). Channels carry
    # actor-state and node-state changes plus user events; this replaces
    # per-entity polling on the subscriber side.
    # ------------------------------------------------------------------
    def _pubsub_cv(self):
        import asyncio as _a

        if self._pubsub_waiters is None:
            self._pubsub_waiters = _a.Condition()
        return self._pubsub_waiters

    async def Publish(self, channel: str, key: str, payload: Any = None) -> dict:
        self._publish(channel, key, payload)
        cv = self._pubsub_cv()
        async with cv:
            cv.notify_all()
        return {"seq": self._pubsub_seq}

    def _publish(self, channel: str, key: str, payload: Any = None) -> None:
        from collections import deque as _dq

        self._pubsub_seq += 1
        q = self.pubsub.setdefault(channel, _dq(maxlen=10000))
        if q.maxlen is not None and len(q) == q.maxlen:
            # the append below evicts q[0]: remember its seq as the
            # channel's dropped floor for gap detection in Subscribe
            self.pubsub_dropped[channel] = q[0][0]
        q.append((self._pubsub_seq, key, payload))

    def _publish_and_wake(self, channel: str, key: str, payload: Any = None) -> None:
        self._publish(channel, key, payload)
        cv = self._pubsub_waiters
        if cv is not None:
            async def _wake():
                async with cv:
                    cv.notify_all()

            asyncio.ensure_future(_wake())

    async def Subscribe(self, channel: str, after_seq: int = 0,
                        timeout_s: float = 20.0) -> dict:
        """Long-poll: return events with seq > after_seq; block until one
        arrives or the timeout lapses."""
        deadline = time.monotonic() + timeout_s
        cv = self._pubsub_cv()
        # Predicate check and wait both under the condition lock — a
        # publish firing between an unlocked check and cv.wait() would
        # otherwise be a lost wakeup (delivery delayed a full timeout).
        async with cv:
            while True:
                q = self.pubsub.get(channel)
                floor = self.pubsub_dropped.get(channel, 0)
                events = [e for e in (q or ()) if e[0] > after_seq]
                if events:
                    return {"events": events, "next_seq": events[-1][0],
                            "dropped_floor": floor}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"events": [], "next_seq": after_seq,
                            "dropped_floor": floor}
                try:
                    await asyncio.wait_for(cv.wait(), timeout=remaining)
                except asyncio.TimeoutError:
                    pass

    def _prometheus_text(self) -> str:
        """Aggregated user metrics + built-in cluster gauges, Prometheus
        text exposition format."""
        out: List[str] = []

        def emit(name, mtype, desc, series_fn):
            out.append(f"# HELP {name} {desc}")
            out.append(f"# TYPE {name} {mtype}")
            out.extend(series_fn())

        def esc(v: str) -> str:
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        def fmt_tags(tags: Dict[str, str], extra: str = "") -> str:
            items = [f'{k}="{esc(v)}"' for k, v in sorted(tags.items())]
            if extra:
                items.append(extra)
            return "{" + ",".join(items) + "}" if items else ""

        # built-ins
        alive = sum(1 for n in self.nodes.values() if n.alive)
        emit("ray_tpu_nodes_alive", "gauge", "Alive nodes",
             lambda: [f"ray_tpu_nodes_alive {alive}"])
        by_state: Dict[str, int] = {}
        for a in self.actors.values():
            by_state[a.state] = by_state.get(a.state, 0) + 1
        emit("ray_tpu_actors", "gauge", "Actors by state", lambda: [
            f'ray_tpu_actors{{state="{s}"}} {c}' for s, c in sorted(by_state.items())
        ])
        ev_state: Dict[str, int] = {}
        for e in self.task_events:
            ev_state[e.get("state", "?")] = ev_state.get(e.get("state", "?"), 0) + 1
        emit("ray_tpu_task_events_total", "counter", "Task events seen", lambda: [
            f'ray_tpu_task_events_total{{state="{s}"}} {c}'
            for s, c in sorted(ev_state.items())
        ])

        # user metrics, merged across producers; producers gone silent for
        # 30s (dead workers) are evicted so the endpoint stays bounded
        now = time.monotonic()
        self.metrics_by_producer = {
            p: (m, ts) for p, (m, ts) in self.metrics_by_producer.items()
            if now - ts < 30.0
        }
        merged: Dict[str, dict] = {}
        for producer, (metrics, _ts) in self.metrics_by_producer.items():
            for m in metrics:
                ent = merged.setdefault(
                    m["name"],
                    {"type": m["type"], "description": m.get("description", ""),
                     "bounds": m.get("bounds"), "series": {}},
                )
                if ent["type"] == "histogram" and m.get("bounds") != ent["bounds"]:
                    continue  # mismatched boundaries can't be merged
                for s in m.get("series", []):
                    key = tuple(sorted(s["tags"].items()))
                    if ent["type"] == "histogram":
                        agg = ent["series"].setdefault(
                            key, {"buckets": [0] * (len(ent["bounds"]) + 1),
                                  "sum": 0.0, "count": 0})
                        agg["buckets"] = [
                            a + b for a, b in zip(agg["buckets"], s["buckets"])
                        ]
                        agg["sum"] += s["sum"]
                        agg["count"] += s["count"]
                    elif ent["type"] == "counter":
                        agg = ent["series"].setdefault(key, {"value": 0.0})
                        agg["value"] += s["value"]
                    else:  # gauge: last writer wins
                        ent["series"][key] = {"value": s["value"]}
        for name, ent in sorted(merged.items()):
            if ent["type"] == "histogram":
                def lines(ent=ent, name=name):
                    ls = []
                    for key, s in ent["series"].items():
                        tags = dict(key)
                        cum = 0
                        for bound, cnt in zip(ent["bounds"], s["buckets"]):
                            cum += cnt
                            le = 'le="%s"' % bound
                            ls.append(
                                f"{name}_bucket{fmt_tags(tags, le)} {cum}"
                            )
                        inf = 'le="+Inf"'
                        ls.append(
                            f"{name}_bucket{fmt_tags(tags, inf)} {s['count']}"
                        )
                        ls.append(f"{name}_sum{fmt_tags(tags)} {s['sum']}")
                        ls.append(f"{name}_count{fmt_tags(tags)} {s['count']}")
                    return ls
            else:
                def lines(ent=ent, name=name):
                    return [
                        f"{name}{fmt_tags(dict(key))} {s['value']}"
                        for key, s in ent["series"].items()
                    ]
            emit(name, ent["type"], ent["description"], lines)
        return "\n".join(out) + "\n"

    async def _serve_metrics_http(self) -> None:
        """Tiny HTTP/1.0 responder: any GET returns the Prometheus text
        (reference: the dashboard agent's Prometheus scrape endpoint)."""

        async def on_client(reader, writer):
            try:
                await reader.readline()  # request line; drain headers
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                body = self._prometheus_text().encode()
                writer.write(
                    b"HTTP/1.0 200 OK\r\n"
                    b"Content-Type: text/plain; version=0.0.4\r\n"
                    + f"Content-Length: {len(body)}\r\n\r\n".encode() + body
                )
                await writer.drain()
            except Exception:  # noqa: BLE001
                pass
            finally:
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        self.metrics_http_port = server.sockets[0].getsockname()[1]
        logger.info("metrics endpoint on :%d", self.metrics_http_port)

    async def Ping(self) -> str:
        return "pong"

    async def run(self) -> None:
        asyncio.ensure_future(self._health_check_loop())
        asyncio.ensure_future(self._flush_loop())
        if self._needs_replay_reschedule:
            asyncio.ensure_future(self._reschedule_replayed())
        await self._serve_metrics_http()
        await self.server.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--storage-path", default="")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(level=args.log_level, format="[gcs] %(levelname)s %(message)s")
    server = GcsServer(args.port, args.storage_path)
    import atexit

    atexit.register(server._flush)
    try:
        asyncio.run(server.run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
