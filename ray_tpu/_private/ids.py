"""Binary IDs for ray_tpu.

Mirrors the reference's ID layout (reference: src/ray/common/id.h,
src/ray/common/id_def.h) so that deterministic object IDs can be derived
from (task id, return index) — required for lineage reconstruction:

- ``JobID``:    4 bytes.
- ``ActorID``:  16 bytes = 12 random + 4 job.
- ``TaskID``:   24 bytes = 8 random + 16 actor (zeros for normal tasks'
  actor part beyond the job suffix).
- ``ObjectID``: 28 bytes = 24 task + 4 little-endian index.
- ``NodeID``/``WorkerID``/``PlacementGroupID``: random fixed-length.
"""

from __future__ import annotations

import os
import struct

from ray_tpu._private import fastpath


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes) -> None:
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_hex(cls, h: str) -> "BaseID":
        return cls(bytes.fromhex(h))

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other: object) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes  # type: ignore[attr-defined]

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._bytes))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class UniqueID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, v: int) -> "JobID":
        return cls(struct.pack("<I", v))


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16
    UNIQUE_BYTES = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(cls.UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])


class TaskID(BaseID):
    SIZE = 24
    UNIQUE_BYTES = 8

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        # actor part = 12 zero bytes + job id, like the reference's
        # TaskID::ForNormalTask (driver/normal tasks carry job in the suffix).
        actor_part = b"\x00" * ActorID.UNIQUE_BYTES + job_id.binary()
        return cls(os.urandom(cls.UNIQUE_BYTES) + actor_part)

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(cls.UNIQUE_BYTES) + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        # Deterministic: the actor creation task id is the actor id
        # prefixed with zeros (reference: TaskID::ForActorCreationTask).
        return cls(b"\x00" * cls.UNIQUE_BYTES + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[self.UNIQUE_BYTES :])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = 28
    INDEX_BYTES = 4

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Deterministic return/put object id (reference: ObjectID::FromIndex).
        Derived on every submit/return — runs on the fastpath codec."""
        return cls(fastpath.id_from_index(task_id.binary(), index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[TaskID.SIZE :])[0]


FunctionID = UniqueID
