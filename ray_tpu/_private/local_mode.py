"""LocalModeRuntime — in-process execution backend.

Reference analogue: python/ray/_private/worker.py LOCAL_MODE. Tasks run on a
thread pool, actors get a dedicated serial executor (or a pool of
``max_concurrency`` threads), objects live in the in-process memory store.
Used by ``init(local_mode=True)`` and as the substrate for unit tests that
don't need process isolation.
"""

from __future__ import annotations

import inspect
import threading
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.core import ActorOptions, CoreRuntime, TaskOptions
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import (
    ActorDiedError,
    RayActorError,
    RayTaskError,
    TaskCancelledError,
)


class _LocalActor:
    def __init__(self, actor_id: ActorID, cls, args, kwargs, opts: ActorOptions):
        self.actor_id = actor_id
        self.opts = opts
        self.dead = False
        from ray_tpu._private.async_compat import (
            ASYNC_ACTOR_DEFAULT_CONCURRENCY,
            has_async_methods,
        )

        self.is_async = has_async_methods(cls)
        n_workers = max(1, opts.max_concurrency)
        if self.is_async and n_workers == 1:
            n_workers = ASYNC_ACTOR_DEFAULT_CONCURRENCY
        self.executor = ThreadPoolExecutor(
            max_workers=n_workers, thread_name_prefix=f"actor-{actor_id.hex()[:8]}"
        )
        self._loop = None
        self._loop_lock = threading.Lock()
        self.instance = None
        self.init_error: Optional[BaseException] = None
        self._init_done = threading.Event()

        def _init():
            try:
                self.instance = cls(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                self.init_error = e
            finally:
                self._init_done.set()

        self.executor.submit(_init)

    def run_call(self, method, args, kwargs):
        """Asyncio actor: EVERY method runs on the actor's event loop —
        coroutines overlap, sync methods serialize on the loop thread
        (single-threaded actor state stays safe)."""
        import asyncio

        with self._loop_lock:
            if self._loop is None:
                self._loop = asyncio.new_event_loop()
                threading.Thread(
                    target=self._loop.run_forever, daemon=True,
                    name=f"actor-loop-{self.actor_id.hex()[:8]}",
                ).start()

        async def _invoke():
            if inspect.iscoroutinefunction(method):
                return await method(*args, **kwargs)
            return method(*args, **kwargs)

        fut = asyncio.run_coroutine_threadsafe(_invoke(), self._loop)
        return fut.result()

    def wait_ready(self, timeout=None) -> None:
        self._init_done.wait(timeout)
        if self.init_error is not None:
            raise self.init_error


class LocalModeRuntime(CoreRuntime):
    def __init__(self, resources: Optional[Dict[str, float]] = None, num_cpus: float = 8):
        self.job_id = JobID.from_int(1)
        self.node_id = NodeID.from_random()
        self.store = MemoryStore()
        self._pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="task")
        self._actors: Dict[ActorID, _LocalActor] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._cancelled: set = set()
        self._task_for_ref: Dict[ObjectID, TaskID] = {}
        self._streams: Dict[TaskID, Any] = {}  # streaming generator states
        self.address = None  # local refs need no owner address
        self._lock = threading.Lock()
        self._resources: Dict[str, float] = {"CPU": float(num_cpus)}
        if resources:
            self._resources.update(resources)
        # detect local TPU chips so resources={"TPU": n} works in local mode
        from ray_tpu.accelerators import tpu as tpu_accel

        n = tpu_accel.TPUAcceleratorManager.get_current_node_num_accelerators()
        if n and "TPU" not in self._resources:
            self._resources["TPU"] = float(n)

    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        w = worker_mod.global_worker
        oid = ObjectID.from_index(w.current_task_id, w.next_put_index())
        self.store.put(oid, value)
        w.reference_counter.add_owned_object(oid)
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        out = []
        for r in refs:
            remaining = None if deadline is None else max(0.0, deadline - _time.monotonic())
            try:
                v = self.store.get(r.id(), timeout=remaining)
            except RayTaskError as e:
                raise e.as_instanceof_cause()
            out.append(v)
        return out

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        futures = [(r, self.store.as_future(r.id())) for r in refs]
        ready: List[ObjectRef] = []
        done_evt = threading.Event()

        def _on_done(_f):
            done_evt.set()

        for _, f in futures:
            f.add_done_callback(_on_done)
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready = [r for r, f in futures if f.done()]
            if len(ready) >= num_returns:
                ready = ready[:num_returns]
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            done_evt.clear()
            wait_t = 0.05 if deadline is None else min(0.05, max(0.0, deadline - time.monotonic()))
            done_evt.wait(wait_t)
        ready_set = {id(r) for r in ready}
        not_ready = [r for r in refs if id(r) not in ready_set]
        return ready, not_ready

    def as_future(self, ref: ObjectRef) -> Future:
        return self.store.as_future(ref.id())

    def free_object(self, oid: ObjectID) -> None:
        self.store.delete(oid)
        self._task_for_ref.pop(oid, None)

    def _abandon_stream(self, task_id: TaskID) -> None:
        st = self._streams.pop(task_id, None)
        if st is None:
            return
        with st.cv:
            oids = list(st.arrived.values())
            st.arrived.clear()
            if st.total is None:
                st.total = st.next_index
            st.cv.notify_all()
        for oid in oids:
            self.free_object(oid)

    # ------------------------------------------------------------------
    def _resolve_args(self, args, kwargs):
        def _res(v):
            if isinstance(v, ObjectRef):
                return self.get([v])[0]
            return v

        return tuple(_res(a) for a in args), {k: _res(v) for k, v in kwargs.items()}

    def _store_returns(self, return_ids: List[ObjectID], result: Any, num_returns: int):
        if num_returns == 1:
            self.store.put(return_ids[0], result)
            return
        try:
            vals = list(result)
        except TypeError:
            vals = [result]
        if len(vals) != num_returns:
            err = RayTaskError(
                "task",
                f"Task returned {len(vals)} values, expected num_returns={num_returns}",
                ValueError(f"expected {num_returns} return values, got {len(vals)}"),
            )
            for oid in return_ids:
                self.store.put(oid, err, is_exception=True)
            return
        for oid, v in zip(return_ids, vals):
            self.store.put(oid, v)

    def submit_task(self, remote_function, args, kwargs, opts: TaskOptions):
        w = worker_mod.global_worker
        task_id = TaskID.for_normal_task(self.job_id)
        if opts.num_returns == "streaming":
            return self._submit_streaming(remote_function, args, kwargs, task_id)
        return_ids = [ObjectID.from_index(task_id, i + 1) for i in range(opts.num_returns)]
        for oid in return_ids:
            w.reference_counter.add_owned_object(oid, pending_creation=True)
        fn = remote_function._function

        def _run():
            if task_id in self._cancelled:
                err = TaskCancelledError(f"Task {task_id.hex()} was cancelled")
                for oid in return_ids:
                    self.store.put(oid, err, is_exception=True)
                return
            try:
                rargs, rkwargs = self._resolve_args(args, kwargs)
                result = fn(*rargs, **rkwargs)
                self._store_returns(return_ids, result, opts.num_returns)
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                err = RayTaskError(remote_function._name, tb, e if isinstance(e, Exception) else None)
                for oid in return_ids:
                    self.store.put(oid, err, is_exception=True)

        self._pool.submit(_run)
        refs = [ObjectRef(oid) for oid in return_ids]
        for oid in return_ids:
            self._task_for_ref[oid] = task_id
        return refs

    def _submit_streaming(self, remote_function, args, kwargs, task_id: TaskID):
        """Streaming generator task, in-process (same ObjectRefGenerator as
        the cluster runtime; yields land in the local store)."""

        def produce():
            rargs, rkwargs = self._resolve_args(args, kwargs)
            return remote_function._function(*rargs, **rkwargs)

        return self._run_stream(self._pool, task_id, remote_function._name, produce)

    def _run_stream(self, executor, task_id: TaskID, name: str, produce):
        """Shared streaming driver: run ``produce()`` (an iterator factory)
        on ``executor``, landing yields in the local store as they appear."""
        from ray_tpu._private.streaming import ObjectRefGenerator, _StreamState

        w = worker_mod.global_worker
        st = _StreamState()
        self._streams[task_id] = st

        def _run():
            idx = 0
            try:
                for value in produce():
                    oid = ObjectID.from_index(task_id, idx + 1)
                    w.reference_counter.add_owned_object(oid)
                    self.store.put(oid, value)
                    abandoned = False
                    with st.cv:
                        if st.total is not None:
                            abandoned = True  # consumer dropped the stream
                        else:
                            st.arrived[idx] = oid
                            st.cv.notify_all()
                    if abandoned:
                        self.free_object(oid)
                        break
                    idx += 1
                with st.cv:
                    if st.total is None:
                        st.total = idx
                    st.cv.notify_all()
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                err = RayTaskError(name, tb, e if isinstance(e, Exception) else None)
                with st.cv:
                    st.error = err.as_instanceof_cause()
                    st.total = idx
                    st.cv.notify_all()

        executor.submit(_run)
        return ObjectRefGenerator(self, task_id, st)

    # ------------------------------------------------------------------
    def create_actor(self, actor_class, args, kwargs, opts: ActorOptions):
        name_key = None
        if opts.name:
            name_key = (opts.namespace or "default", opts.name)
            with self._lock:
                existing = self._named_actors.get(name_key)
                if existing is not None:
                    if opts.get_if_exists:
                        return existing
                    raise ValueError(f"Actor with name {opts.name!r} already exists")
        actor_id = ActorID.of(self.job_id)
        actor = _LocalActor(actor_id, actor_class._cls, args, kwargs, opts)
        with self._lock:
            self._actors[actor_id] = actor
            if name_key:
                self._named_actors[name_key] = actor_id
        return actor_id

    def submit_actor_task(self, handle, method_name, args, kwargs, opts: TaskOptions):
        actor = self._actors.get(handle._actor_id)
        task_id = TaskID.for_actor_task(handle._actor_id)
        streaming = opts.num_returns == "streaming"
        n_returns = 0 if streaming else opts.num_returns
        return_ids = [ObjectID.from_index(task_id, i + 1) for i in range(n_returns)]
        w = worker_mod.global_worker
        for oid in return_ids:
            w.reference_counter.add_owned_object(oid, pending_creation=True)
        if actor is None or actor.dead:
            err = ActorDiedError()
            if streaming:
                raise err
            for oid in return_ids:
                self.store.put(oid, err, is_exception=True)
            return [ObjectRef(oid) for oid in return_ids]

        if streaming:
            return self._submit_actor_streaming(actor, method_name, args, kwargs, task_id)

        def _run():
            try:
                actor.wait_ready()
            except BaseException as e:  # noqa: BLE001
                err = RayActorError(f"Actor creation failed: {e!r}")
                for oid in return_ids:
                    self.store.put(oid, err, is_exception=True)
                return
            try:
                rargs, rkwargs = self._resolve_args(args, kwargs)
                method = getattr(actor.instance, method_name)
                if actor.is_async:
                    result = actor.run_call(method, rargs, rkwargs)
                else:
                    result = method(*rargs, **rkwargs)
                self._store_returns(return_ids, result, opts.num_returns)
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                err = RayTaskError(method_name, tb, e if isinstance(e, Exception) else None)
                for oid in return_ids:
                    self.store.put(oid, err, is_exception=True)

        actor.executor.submit(_run)
        return [ObjectRef(oid) for oid in return_ids]

    def _submit_actor_streaming(self, actor, method_name, args, kwargs, task_id: TaskID):
        def produce():
            actor.wait_ready()
            rargs, rkwargs = self._resolve_args(args, kwargs)
            return getattr(actor.instance, method_name)(*rargs, **rkwargs)

        return self._run_stream(actor.executor, task_id, method_name, produce)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            actor = self._actors.pop(actor_id, None)
            for k, v in list(self._named_actors.items()):
                if v == actor_id:
                    del self._named_actors[k]
        if actor:
            actor.dead = True
            actor.executor.shutdown(wait=False, cancel_futures=True)

    def get_actor(self, name: str, namespace: Optional[str] = None):
        with self._lock:
            actor_id = self._named_actors.get((namespace or "default", name))
        if actor_id is None:
            raise ValueError(f"Failed to look up actor with name '{name}'")
        return actor_id

    def cancel(self, ref: ObjectRef, force=False, recursive=True) -> None:
        tid = self._task_for_ref.get(ref.id())
        if tid is not None:
            self._cancelled.add(tid)

    # ------------------------------------------------------------------
    def cluster_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def available_resources(self) -> Dict[str, float]:
        return dict(self._resources)

    def nodes(self) -> List[Dict[str, Any]]:
        return [
            {
                "NodeID": self.node_id.hex(),
                "Alive": True,
                "NodeManagerAddress": "127.0.0.1",
                "Resources": dict(self._resources),
            }
        ]

    def shutdown(self) -> None:
        for actor in list(self._actors.values()):
            actor.dead = True
            actor.executor.shutdown(wait=False, cancel_futures=True)
        self._actors.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)
