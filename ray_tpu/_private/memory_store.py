"""In-process memory store for small objects + pending-result futures.

Reference: src/ray/core_worker/store_provider/memory_store/memory_store.h:48
(CoreWorkerMemoryStore). Small task returns and errors land here on the
*owner* worker; ``get`` blocks on a per-object condition until the value
arrives or a timeout fires.
"""

from __future__ import annotations

import threading
import concurrent.futures
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from ray_tpu._private import debug_locks
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError


class _Entry:
    __slots__ = ("value", "is_exception")

    def __init__(self, value: Any, is_exception: bool):
        self.value = value
        self.is_exception = is_exception


class MemoryStore:
    def __init__(self) -> None:
        self._lock = debug_locks.maybe_wrap(
            threading.Lock(), "memory_store.MemoryStore._lock")
        self._objects: Dict[ObjectID, _Entry] = {}
        self._waiters: Dict[ObjectID, List[Future]] = {}

    def put(self, oid: ObjectID, value: Any, is_exception: bool = False) -> None:
        with self._lock:
            self._objects[oid] = _Entry(value, is_exception)
            waiters = self._waiters.pop(oid, [])
        for f in waiters:
            if not f.done():
                if is_exception:
                    f.set_exception(value)
                else:
                    f.set_result(value)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._objects

    def get_if_exists(self, oid: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._objects.get(oid)

    def as_future(self, oid: ObjectID) -> Future:
        f: Future = Future()
        with self._lock:
            e = self._objects.get(oid)
            if e is None:
                self._waiters.setdefault(oid, []).append(f)
                return f
        if e.is_exception:
            f.set_exception(e.value)
        else:
            f.set_result(e.value)
        return f

    def get(self, oid: ObjectID, timeout: Optional[float] = None) -> Any:
        f = self.as_future(oid)
        try:
            return f.result(timeout=timeout)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # 3.10: futures.TimeoutError is not the builtin — catch both
            raise GetTimeoutError(f"Get timed out for object {oid.hex()}")

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._objects.pop(oid, None)
            self._waiters.pop(oid, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
