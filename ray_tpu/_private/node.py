"""Node bootstrap — starts/stops the head node's processes.

Reference: python/ray/_private/node.py (Node.start_head_processes :1364 —
spawns gcs_server; start_ray_processes :1393 — spawns raylet which hosts
plasma) and services.py process management.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, Optional, Tuple

import psutil

from ray_tpu._private.config import config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.rpc import RpcClient

logger = logging.getLogger(__name__)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def default_node_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    from ray_tpu.accelerators import get_all_accelerator_managers

    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus) if num_cpus is not None else float(os.cpu_count() or 1)
    if num_tpus is not None:
        out["TPU"] = float(num_tpus)
    # every registered backend detects through the same ABC (reference:
    # _private/accelerators — 8 plugins behind one surface)
    for name, mgr in get_all_accelerator_managers().items():
        if name not in out:
            n = mgr.get_current_node_num_accelerators()
            if n:
                out[name] = float(n)
        out.update(mgr.get_current_node_additional_resources())
    out.setdefault("memory", float(psutil.virtual_memory().available // 2))
    node_ip = "127.0.0.1"
    out[f"node:{node_ip}"] = 1.0
    return out


def spawn_gcs(port: int, session_dir: str, log_name: str = "gcs.log") -> subprocess.Popen:
    """Spawn the GCS server process and wait until it answers Ping."""
    env = dict(os.environ)
    env["RAY_TPU_CONFIG_JSON"] = config.to_json()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [repo_root, env.get("PYTHONPATH", "")] if p
    )
    gcs_log = open(os.path.join(session_dir, log_name), "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu._private.gcs.server",
            "--port", str(port),
            "--storage-path", config.gcs_storage_path,
        ],
        env=env,
        stdout=gcs_log,
        stderr=subprocess.STDOUT,
    )
    client = RpcClient("127.0.0.1", port)
    # generous: a loaded CI box (a full suite's worth of processes on
    # one core) can take >30s just to schedule the interpreter start
    deadline = time.monotonic() + 60
    try:
        while True:
            try:
                client.call("Ping", timeout=2)
                return proc
            except Exception:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"GCS exited with {proc.returncode}; see {session_dir}/{log_name}"
                    )
                if time.monotonic() > deadline:
                    raise RuntimeError("GCS did not become ready")
                time.sleep(0.05)
    finally:
        # probe client: close (cancel + await its read loop) rather than
        # abandoning the task to be GC'd mid-read ("Task was destroyed")
        client.close()


def spawn_raylet(
    gcs_addr: Tuple[str, int],
    node_id: str,
    resources: Dict[str, float],
    store_socket: str,
    store_capacity: int,
    session_dir: str,
    is_head: bool = False,
    log_name: str = "raylet.log",
    labels: Optional[Dict[str, str]] = None,
) -> Tuple[subprocess.Popen, int]:
    """Spawn a raylet daemon process and wait for its port file.

    Shared by the single-node Node bootstrap and the multi-node test
    harness (reference: cluster_utils.Cluster add_node, cluster_utils.py:208).
    """
    env = dict(os.environ)
    env["RAY_TPU_CONFIG_JSON"] = config.to_json()
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [repo_root, env.get("PYTHONPATH", "")] if p
    )
    port_file = os.path.join(session_dir, "raylet_port")
    raylet_log = open(os.path.join(session_dir, log_name), "ab")
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu._private.raylet.raylet",
        "--node-id", node_id,
        "--gcs-addr", f"{gcs_addr[0]}:{gcs_addr[1]}",
        "--resources-json", json.dumps(resources),
        "--store-socket", store_socket,
        "--store-capacity", str(store_capacity),
        "--session-dir", session_dir,
        "--port-file", port_file,
        "--log-level", os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
    ]
    if is_head:
        cmd.append("--is-head")
    if labels:
        cmd.extend(["--labels-json", json.dumps(labels)])
    proc = subprocess.Popen(cmd, env=env, stdout=raylet_log, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise RuntimeError(
                f"raylet exited with {proc.returncode}; see {session_dir}/{log_name}"
            )
        if time.monotonic() > deadline:
            raise RuntimeError("raylet failed to start in time")
        time.sleep(0.02)
    with open(port_file) as f:
        port = int(f.read().strip())
    os.remove(port_file)
    return proc, port


def kill_process_tree(proc: subprocess.Popen, force: bool = False) -> None:
    """Terminate a daemon process and everything it spawned (store daemon,
    worker processes)."""
    if proc is None or proc.poll() is not None:
        return
    try:
        parent = psutil.Process(proc.pid)
        children = parent.children(recursive=True)
        if force:
            proc.kill()
        else:
            proc.terminate()
        try:
            proc.wait(timeout=3)
        except subprocess.TimeoutExpired:
            proc.kill()
        for c in children:
            try:
                c.kill() if force else c.terminate()
            except psutil.Error:
                pass
        _, alive = psutil.wait_procs(children, timeout=2)
        for c in alive:
            try:
                c.kill()
            except psutil.Error:
                pass
    except (psutil.Error, OSError):
        pass


class Node:
    """Manages head-node child processes: GCS, raylet (which owns the
    object-store daemon and workers)."""

    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
    ):
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
        self.node_id = NodeID.from_random().hex()
        self.gcs_port = config.gcs_port or _free_port()
        self.gcs_addr: Tuple[str, int] = ("127.0.0.1", self.gcs_port)
        self.store_socket = os.path.join(self.session_dir, "store.sock")
        self.store_capacity = int(object_store_memory or config.object_store_memory_bytes)
        self.resources = default_node_resources(num_cpus, num_tpus, resources)
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.raylet_port: Optional[int] = None

    @property
    def raylet_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.raylet_port)

    def start(self) -> None:
        self.gcs_proc = spawn_gcs(self.gcs_port, self.session_dir)
        self.raylet_proc, self.raylet_port = spawn_raylet(
            gcs_addr=self.gcs_addr,
            node_id=self.node_id,
            resources=self.resources,
            store_socket=self.store_socket,
            store_capacity=self.store_capacity,
            session_dir=self.session_dir,
            is_head=True,
        )
        atexit.register(self.stop)

    def _wait_rpc_ready(self, addr: Tuple[str, int], name: str, timeout: float = 30.0) -> None:
        client = RpcClient(addr[0], addr[1])
        deadline = time.monotonic() + timeout
        try:
            while True:
                try:
                    client.call("Ping", timeout=2)
                    return
                except Exception:
                    if time.monotonic() > deadline:
                        raise RuntimeError(f"{name} did not become ready at {addr}")
                    time.sleep(0.05)
        finally:
            client.close()

    def stop(self) -> None:
        # kill whole trees (the raylet owns the store daemon + workers)
        kill_process_tree(self.raylet_proc)
        kill_process_tree(self.gcs_proc)
        self.raylet_proc = None
        self.gcs_proc = None
