"""Node bootstrap — starts/stops the head node's processes.

Reference: python/ray/_private/node.py (Node.start_head_processes :1364 —
spawns gcs_server; start_ray_processes :1393 — spawns raylet which hosts
plasma) and services.py process management.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Dict, Optional, Tuple

import psutil

from ray_tpu._private.config import config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.rpc import RpcClient

logger = logging.getLogger(__name__)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def default_node_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    from ray_tpu.accelerators import tpu as tpu_accel

    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus) if num_cpus is not None else float(os.cpu_count() or 1)
    if num_tpus is not None:
        out["TPU"] = float(num_tpus)
    else:
        n = tpu_accel.TPUAcceleratorManager.get_current_node_num_accelerators()
        if n:
            out["TPU"] = float(n)
    out.setdefault("memory", float(psutil.virtual_memory().available // 2))
    out.update(tpu_accel.TPUAcceleratorManager.get_current_node_additional_resources())
    node_ip = "127.0.0.1"
    out[f"node:{node_ip}"] = 1.0
    return out


class Node:
    """Manages head-node child processes: GCS, raylet (which owns the
    object-store daemon and workers)."""

    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
    ):
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
        self.node_id = NodeID.from_random().hex()
        self.gcs_port = config.gcs_port or _free_port()
        self.gcs_addr: Tuple[str, int] = ("127.0.0.1", self.gcs_port)
        self.store_socket = os.path.join(self.session_dir, "store.sock")
        self.store_capacity = int(object_store_memory or config.object_store_memory_bytes)
        self.resources = default_node_resources(num_cpus, num_tpus, resources)
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.raylet_port: Optional[int] = None

    @property
    def raylet_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.raylet_port)

    def start(self) -> None:
        env = dict(os.environ)
        env["RAY_TPU_CONFIG_JSON"] = config.to_json()
        pythonpath = os.pathsep.join(
            p for p in [os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), env.get("PYTHONPATH", "")] if p
        )
        env["PYTHONPATH"] = pythonpath
        gcs_log = open(os.path.join(self.session_dir, "gcs.log"), "ab")
        self.gcs_proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.gcs.server",
                "--port",
                str(self.gcs_port),
                "--storage-path",
                config.gcs_storage_path,
            ],
            env=env,
            stdout=gcs_log,
            stderr=subprocess.STDOUT,
        )
        self._wait_rpc_ready(self.gcs_addr, "GCS")

        port_file = os.path.join(self.session_dir, "raylet_port")
        raylet_log = open(os.path.join(self.session_dir, "raylet.log"), "ab")
        self.raylet_proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.raylet.raylet",
                "--node-id",
                self.node_id,
                "--gcs-addr",
                f"{self.gcs_addr[0]}:{self.gcs_addr[1]}",
                "--resources-json",
                json.dumps(self.resources),
                "--store-socket",
                self.store_socket,
                "--store-capacity",
                str(self.store_capacity),
                "--is-head",
                "--session-dir",
                self.session_dir,
                "--port-file",
                port_file,
                "--log-level",
                os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
            ],
            env=env,
            stdout=raylet_log,
            stderr=subprocess.STDOUT,
        )
        deadline = time.monotonic() + 30
        while not os.path.exists(port_file):
            if self.raylet_proc.poll() is not None:
                raise RuntimeError(
                    f"raylet exited with {self.raylet_proc.returncode}; "
                    f"see {self.session_dir}/raylet.log"
                )
            if time.monotonic() > deadline:
                raise RuntimeError("raylet failed to start in time")
            time.sleep(0.02)
        with open(port_file) as f:
            self.raylet_port = int(f.read().strip())
        atexit.register(self.stop)

    def _wait_rpc_ready(self, addr: Tuple[str, int], name: str, timeout: float = 30.0) -> None:
        client = RpcClient(addr[0], addr[1])
        deadline = time.monotonic() + timeout
        while True:
            try:
                client.call("Ping", timeout=2)
                return
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"{name} did not become ready at {addr}")
                time.sleep(0.05)

    def stop(self) -> None:
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is None or proc.poll() is not None:
                continue
            try:
                # kill the whole tree (raylet owns store + workers)
                parent = psutil.Process(proc.pid)
                children = parent.children(recursive=True)
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
                for c in children:
                    try:
                        c.terminate()
                    except psutil.Error:
                        pass
                _, alive = psutil.wait_procs(children, timeout=2)
                for c in alive:
                    try:
                        c.kill()
                    except psutil.Error:
                        pass
            except (psutil.Error, OSError):
                pass
        self.raylet_proc = None
        self.gcs_proc = None
