"""ObjectRef — the future/handle for a ray_tpu object.

Reference: python/ray/includes/object_ref.pxi and src/ray/common/id.h.
An ObjectRef carries its id plus owner metadata (the address of the worker
that owns the object's lifetime — reference ownership model:
src/ray/core_worker/reference_counter.h:44). Serializing a ref through a
task argument registers a borrow with the owner.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_call_site", "__weakref__")

    def __init__(
        self,
        object_id: ObjectID,
        owner_addr: Optional[Tuple[str, int]] = None,
        call_site: str = "",
    ) -> None:
        self._id = object_id
        self._owner_addr = owner_addr
        self._call_site = call_site
        # Register with the current worker's reference counter, if connected.
        from ray_tpu._private import worker as _worker_mod

        w = _worker_mod.global_worker
        if w is not None and w.connected:
            w.reference_counter.add_local_reference(self._id)
            # Borrowed ref (constructed from a deserialized payload in a
            # process that doesn't own it): register with the owner so it
            # keeps the object alive (reference_counter.h:44 borrowers).
            if owner_addr is not None:
                core = getattr(w, "core", None)
                if core is not None and hasattr(core, "on_ref_created"):
                    core.on_ref_created(self._id, tuple(owner_addr))

    # -- identity ---------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    @property
    def owner_address(self) -> Optional[Tuple[str, int]]:
        return self._owner_addr

    # -- lifecycle --------------------------------------------------------
    def __del__(self) -> None:
        try:
            from ray_tpu._private import worker as _worker_mod

            w = _worker_mod.global_worker
            if w is not None and w.connected:
                w.reference_counter.remove_local_reference(self._id)
        except Exception:
            pass  # __del__ during interpreter teardown: modules half-gone

    # -- pickling: refs travel with owner metadata ------------------------
    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner_addr, self._call_site))

    # -- conveniences -----------------------------------------------------
    def future(self):
        """Return a concurrent.futures.Future resolved with the value."""
        from ray_tpu._private import worker as _worker_mod

        return _worker_mod.global_worker.core.as_future(self)

    def __await__(self):
        from ray_tpu._private.async_compat import as_asyncio_future

        return as_asyncio_future(self).__await__()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"
