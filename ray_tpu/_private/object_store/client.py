"""Python client for the native shared-memory object store.

Reference analogue: src/ray/object_manager/plasma/client.h (PlasmaClient::
Get/CreateAndSpillIfNeeded/Seal). The C++ daemon (src/object_store/store.cc)
owns the pool; this client receives the pool fd once at connect (SCM_RIGHTS,
like plasma's fling.cc) and mmaps it, so Get() returns zero-copy memoryviews
into shared memory.

Thread-safe: one socket, one lock; calls are request/response.
"""

from __future__ import annotations

import array
import mmap
import os
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

(MSG_CONNECT, MSG_CREATE, MSG_SEAL, MSG_GET, MSG_RELEASE, MSG_CONTAINS,
 MSG_DELETE, MSG_METRICS, MSG_ABORT, MSG_LIST) = range(1, 11)
ST_OK, ST_FULL, ST_EXISTS, ST_NOT_FOUND, ST_NOT_SEALED, ST_TIMEOUT, ST_IN_USE = 0, -1, -2, -3, -4, -5, -6

_ID_SIZE = 28


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def store_binary_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build", "ray_tpu_store")


def ensure_store_built() -> str:
    """Build the C++ store daemon on first use (g++ is in the image)."""
    path = store_binary_path()
    src = os.path.join(_repo_root(), "src", "object_store", "store.cc")
    if os.path.exists(path) and os.path.getmtime(path) >= os.path.getmtime(src):
        return path
    subprocess.run(
        ["make", "-C", os.path.join(_repo_root(), "src", "object_store")],
        check=True,
        capture_output=True,
    )
    return path


def start_store_process(
    socket_path: str, capacity: int, no_evict: bool = False
) -> subprocess.Popen:
    binary = ensure_store_built()
    cmd = [binary, socket_path, str(capacity)]
    if no_evict:
        # FULL instead of silent LRU drop; the raylet spills to disk
        cmd.append("no-evict")
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 10
    while not os.path.exists(socket_path):
        if proc.poll() is not None:
            raise RuntimeError(f"object store daemon exited with {proc.returncode}")
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError("object store daemon failed to start")
        time.sleep(0.005)
    return proc


class PlasmaBuffer:
    """A created-but-unsealed object: write into .data then seal()."""

    def __init__(self, client: "StoreClient", oid: ObjectID, offset: int, size: int):
        self._client = client
        self.object_id = oid
        self.data = memoryview(client._pool)[offset : offset + size]
        self._sealed = False

    def seal(self) -> None:
        self._client.seal(self.object_id)
        self._sealed = True

    def abort(self) -> None:
        if not self._sealed:
            self._client.abort(self.object_id)


class StoreClient:
    def __init__(self, socket_path: str):
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        deadline = time.monotonic() + 10
        while True:
            try:
                self._sock.connect(socket_path)
                break
            except (FileNotFoundError, ConnectionRefusedError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.01)
        self._send(MSG_CONNECT, b"")
        # reply carries the pool fd via SCM_RIGHTS
        fds = array.array("i")
        msg, ancdata, _, _ = self._sock.recvmsg(13, socket.CMSG_SPACE(4))
        while len(msg) < 13:
            chunk, anc2, _, _ = self._sock.recvmsg(13 - len(msg), socket.CMSG_SPACE(4))
            msg += chunk
            ancdata.extend(anc2)
        for level, ctype, data in ancdata:
            if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                fds.frombytes(data[: len(data) - (len(data) % 4)])
        (payload_len,) = struct.unpack_from("<I", msg, 0)
        assert msg[4] == MSG_CONNECT and payload_len == 8
        (self.pool_size,) = struct.unpack_from("<Q", msg, 5)
        if not fds:
            raise RuntimeError("store did not pass pool fd")
        self._pool_fd = fds[0]
        self._pool = mmap.mmap(self._pool_fd, self.pool_size)

    # -- low-level framing -------------------------------------------------
    def _send(self, msg_type: int, payload: bytes) -> None:
        frame = struct.pack("<IB", len(payload), msg_type) + payload
        self._sock.sendall(frame)

    def _recv_reply(self, expect_type: int) -> bytes:
        header = self._recv_exact(5)
        (length,) = struct.unpack_from("<I", header, 0)
        mtype = header[4]
        payload = self._recv_exact(length)
        assert mtype == expect_type, f"expected msg {expect_type}, got {mtype}"
        return payload

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("object store connection closed")
            buf += chunk
        return buf

    def _call(self, msg_type: int, payload: bytes) -> bytes:
        with self._lock:
            self._send(msg_type, payload)
            return self._recv_reply(msg_type)

    # -- API ---------------------------------------------------------------
    def create(self, oid: ObjectID, size: int) -> PlasmaBuffer:
        reply = self._call(MSG_CREATE, oid.binary() + struct.pack("<Q", size))
        status, offset = struct.unpack("<iQ", reply)
        if status == ST_FULL:
            raise ObjectStoreFullError(
                f"Object store is full (requested {size} bytes, capacity {self.pool_size})"
            )
        if status == ST_EXISTS:
            raise FileExistsError(f"Object {oid.hex()} already exists in the store")
        return PlasmaBuffer(self, oid, offset, size)

    def put_bytes(self, oid: ObjectID, data: "bytes | memoryview") -> None:
        buf = self.create(oid, len(data))
        buf.data[:] = data
        buf.seal()

    def seal(self, oid: ObjectID) -> None:
        reply = self._call(MSG_SEAL, oid.binary())
        (status,) = struct.unpack("<i", reply)
        if status != ST_OK:
            raise KeyError(f"seal: object {oid.hex()} not found")

    def abort(self, oid: ObjectID) -> None:
        self._call(MSG_ABORT, oid.binary())

    def get(
        self, oids: List[ObjectID], timeout_ms: int = -1
    ) -> List[Optional[memoryview]]:
        """Fetch sealed objects; returns zero-copy views (None on timeout).

        Each returned view holds a server-side pin; call release() when done.
        """
        payload = struct.pack("<I", len(oids))
        for oid in oids:
            payload += oid.binary()
        payload += struct.pack("<q", timeout_ms)
        reply = self._call(MSG_GET, payload)
        (n,) = struct.unpack_from("<I", reply, 0)
        out: List[Optional[memoryview]] = []
        off = 4
        pool_view = memoryview(self._pool)
        for _ in range(n):
            status, offset, size = struct.unpack_from("<iQQ", reply, off)
            off += 20
            if status == ST_OK:
                out.append(pool_view[offset : offset + size])
            else:
                out.append(None)
        return out

    def release(self, oid: ObjectID) -> None:
        self._call(MSG_RELEASE, oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        return self.contains_state(oid) == 0

    def contains_state(self, oid: ObjectID) -> int:
        """0 = sealed, 1 = created-but-unsealed, 2 = absent."""
        reply = self._call(MSG_CONTAINS, oid.binary())
        (status,) = struct.unpack("<i", reply)
        return status

    def delete(self, oid: ObjectID) -> int:
        """Returns the store status (ST_OK, ST_NOT_FOUND, or ST_IN_USE —
        the latter defers the delete to the last pin release)."""
        reply = self._call(MSG_DELETE, oid.binary())
        (status,) = struct.unpack("<i", reply)
        return status

    def list_objects(self) -> List[Tuple[bytes, int, bool, bool]]:
        """All objects, LRU-oldest first: (id_bytes, size, sealed, pinned).
        Feeds the raylet's spill-candidate selection."""
        reply = self._call(MSG_LIST, b"")
        (n,) = struct.unpack_from("<I", reply, 0)
        out: List[Tuple[bytes, int, bool, bool]] = []
        off = 4
        for _ in range(n):
            oid = bytes(reply[off : off + _ID_SIZE])
            size, sealed, pinned = struct.unpack_from("<QBB", reply, off + _ID_SIZE)
            off += _ID_SIZE + 10
            out.append((oid, size, bool(sealed), bool(pinned)))
        return out

    def metrics(self) -> Dict[str, int]:
        reply = self._call(MSG_METRICS, b"")
        cap, alloc, nobj, nevict, bevict = struct.unpack("<QQQQQ", reply)
        return {
            "capacity": cap,
            "allocated": alloc,
            "num_objects": nobj,
            "num_evictions": nevict,
            "bytes_evicted": bevict,
        }

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._pool.close()
        except (BufferError, ValueError):
            pass  # outstanding memoryviews keep the map alive
        try:
            os.close(self._pool_fd)
        except OSError:
            pass
