"""Profiling: task timeline export + TPU (jax.profiler) hooks.

Reference: python/ray/_private/profiling.py (`ray.timeline` → Chrome
trace of task lifetimes from GcsTaskManager events) and the runtime-env
GPU profiler plugins (_private/runtime_env/nsight.py) — the TPU
equivalent wraps jax.profiler/xprof traces.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def timeline(filename: Optional[str] = None) -> Optional[List[Dict[str, Any]]]:
    """Chrome-trace events of task execution (open in chrome://tracing
    or Perfetto). Spans: queued (SUBMITTED→RUNNING) and execution
    (RUNNING→FINISHED/FAILED); tasks missing a RUNNING event fall back
    to one SUBMITTED→end span.

    Reference surface: ray.timeline(_private/profiling.py).
    """
    from ray_tpu.util.state import list_tasks

    by_task: Dict[str, Dict[str, dict]] = {}
    for ev in list_tasks(limit=20000):
        by_task.setdefault(ev["task_id"], {})[ev["state"]] = ev
    events: List[Dict[str, Any]] = []
    for tid, states in by_task.items():
        sub = states.get("SUBMITTED")
        run = states.get("RUNNING")
        end = states.get("FINISHED") or states.get("FAILED")
        name = (end or run or sub or {}).get("name", "?")
        failed = "FAILED" in states
        if sub and run:
            events.append({
                "name": f"queued:{name}", "cat": "queue", "ph": "X",
                "ts": sub["ts"] * 1e6,
                "dur": max(0.0, (run["ts"] - sub["ts"]) * 1e6),
                "pid": sub.get("job_id", "job"),
                "tid": run.get("worker", "worker"),
                "args": {"task_id": tid},
            })
        start = run or sub
        if start and end:
            events.append({
                "name": name, "cat": "task", "ph": "X",
                "ts": start["ts"] * 1e6,
                "dur": max(0.0, (end["ts"] - start["ts"]) * 1e6),
                "pid": start.get("job_id", "job"),
                "tid": (run or end).get("worker", "worker"),
                "args": {"task_id": tid, "state": end["state"],
                         "failed": failed},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
        return None
    return events


# ---------------------------------------------------------------------------
# TPU device profiling (jax.profiler / xprof)
# ---------------------------------------------------------------------------
_trace_active = False


def start_tpu_profile(logdir: str) -> None:
    """Start a jax.profiler trace (view in XProf/TensorBoard). The TPU
    analogue of the reference's GPU profiler runtime-env plugins."""
    global _trace_active
    import jax

    jax.profiler.start_trace(logdir)
    _trace_active = True


def stop_tpu_profile() -> None:
    global _trace_active
    import jax

    if _trace_active:
        jax.profiler.stop_trace()
        _trace_active = False


class tpu_profile:
    """Context manager: ``with ray_tpu.tpu_profile("/tmp/trace"): step()``"""

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        start_tpu_profile(self.logdir)
        return self

    def __exit__(self, *exc):
        stop_tpu_profile()
        return False
