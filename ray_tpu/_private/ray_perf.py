"""Microbenchmark suite (reference: python/ray/_private/ray_perf.py:95-290
and release/microbenchmark/run_microbenchmark.py).

Measures the core-runtime hot paths in ops/s: plasma put/get, task
submission, sync/async actor calls, channels. Run directly:

    python -m ray_tpu._private.ray_perf [--small]

Prints one line per benchmark plus a JSON summary.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List

import numpy as np


def timeit(name: str, fn: Callable, multiplier: int = 1,
           duration_s: float = 2.0, warmup: int = 3) -> Dict:
    for _ in range(warmup):
        fn()
    count = 0
    start = time.perf_counter()
    while time.perf_counter() - start < duration_s:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    print(f"{name:<44s} {rate:>12,.1f} ops/s")
    return {"name": name, "ops_per_s": rate}


def main(small: bool = False) -> List[Dict]:
    import ray_tpu

    init_info = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    owns_runtime = not init_info.get("already_initialized")
    results: List[Dict] = []
    dur = 0.5 if small else 2.0

    # -- object store ---------------------------------------------------
    arr_small = np.zeros(100, np.float32)
    arr_1mb = np.zeros((512, 512), np.float32)

    def put_small():
        ray_tpu.put(arr_small)

    results.append(timeit("single client put (400B)", put_small,
                          duration_s=dur))

    def put_1mb():
        ray_tpu.put(arr_1mb)

    results.append(timeit("single client put (1MB)", put_1mb,
                          duration_s=dur))

    ref_small = ray_tpu.put(arr_small)
    ref_1mb = ray_tpu.put(arr_1mb)

    def get_small():
        ray_tpu.get(ref_small)

    results.append(timeit("single client get (400B)", get_small,
                          duration_s=dur))

    def get_1mb():
        ray_tpu.get(ref_1mb)

    results.append(timeit("single client get (1MB)", get_1mb,
                          duration_s=dur))

    # -- tasks ----------------------------------------------------------
    @ray_tpu.remote
    def tiny(x):
        return x

    def tasks_sync():
        ray_tpu.get(tiny.remote(0))

    results.append(timeit("tasks sync (roundtrip)", tasks_sync,
                          duration_s=dur))

    batch = 100 if small else 1000

    def tasks_batch():
        ray_tpu.get([tiny.remote(i) for i in range(batch)])

    results.append(timeit(f"tasks async batch ({batch})", tasks_batch,
                          multiplier=batch, duration_s=dur))

    # -- actors ---------------------------------------------------------
    @ray_tpu.remote
    class Actor:
        def m(self, x):
            return x

    a = Actor.remote()
    ray_tpu.get(a.m.remote(0))

    def actor_sync():
        ray_tpu.get(a.m.remote(0))

    results.append(timeit("1:1 actor calls sync", actor_sync,
                          duration_s=dur))

    def actor_async():
        ray_tpu.get([a.m.remote(i) for i in range(batch)])

    results.append(timeit(f"1:1 actor calls async ({batch})", actor_async,
                          multiplier=batch, duration_s=dur))

    b = Actor.options(max_concurrency=8).remote()
    ray_tpu.get(b.m.remote(0))

    def actor_conc():
        ray_tpu.get([b.m.remote(i) for i in range(batch)])

    results.append(timeit(f"1:1 async-actor calls ({batch})", actor_conc,
                          multiplier=batch, duration_s=dur))

    # -- channels (compiled-DAG transport) -------------------------------
    from ray_tpu.experimental import Channel, TensorChannel

    ch = Channel(capacity=1 << 16)
    rd = ch.reader()

    def chan_rt():
        ch.write(0)
        rd.read()

    results.append(timeit("channel write+read (pickle)", chan_rt,
                          duration_s=dur))
    ch.close()

    tch = TensorChannel((512, 512), "float32")
    trd = tch.reader()

    def tchan_rt():
        # the zero-copy data plane's consumption pattern (what the
        # pipelined collectives do): borrow the slot view, consume it in
        # place, release — payload bytes move exactly once, writer → shm
        tch.write(arr_1mb)
        v = trd.read_view()
        consumed = v[0, 0]  # touch: the view IS the data
        trd.release()
        return consumed

    results.append(timeit("tensor channel write+read (1MB)", tchan_rt,
                          duration_s=dur))
    tch.close()

    # -- actor bring-up (lease + zygote spawn + CreateActor + resolve) --
    # Burst-create a fleet and wait for every first ping — the
    # scale_bench many_actors shape, miniaturized; the per-node creation
    # gate + PrestartWorkers spawn overlap is what this row measures.
    # Kills and worker teardown happen OUTSIDE the timed window (the
    # envelope metric is creation, not churn), and the row runs LAST so
    # its worker churn cannot pollute the other measurements. Best of
    # three windows: bring-up shares the host's one core with the whole
    # control plane, so individual windows swing with scheduler luck.
    n_create = 10

    @ray_tpu.remote(num_cpus=0)
    class Spawned:
        def ping(self):
            return 1

    best = 0.0
    for _ in range(2 if small else 3):
        t0 = time.perf_counter()
        fleet = [Spawned.remote() for _ in range(n_create)]
        ray_tpu.get([x.ping.remote() for x in fleet])
        best = max(best, n_create / (time.perf_counter() - t0))
        for x in fleet:
            ray_tpu.kill(x)
        time.sleep(1.0)  # let the killed fleet's workers exit
    name = f"actor create+first-ping ({n_create})"
    print(f"{name:<44s} {best:>12,.1f} ops/s")
    results.append({"name": name, "ops_per_s": best})

    ray_tpu.kill(a)
    ray_tpu.kill(b)
    print(json.dumps({r["name"]: round(r["ops_per_s"], 1)
                      for r in results}))
    if owns_runtime:  # never tear down a caller's cluster
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    import sys

    main(small="--small" in sys.argv)
