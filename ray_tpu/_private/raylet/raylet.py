"""Raylet — the per-node daemon: worker pool + local scheduler + leases.

Reference: src/ray/raylet/ — NodeManager (node_manager.h:144, lease RPCs
node_manager.cc:1834/2136), WorkerPool (worker_pool.h:280 PopWorker/
PrestartWorkers), scheduling (cluster_lease_manager.cc:45 queue, :194
schedule-and-grant), PlacementGroupResourceManager (2PC bundle reserve).

TPU-first: the resource set tracks individual TPU chip ids; a lease that
asks for ``TPU: n`` is granted concrete chips and its worker gets
``TPU_VISIBLE_CHIPS`` set, generalizing the reference's accelerator-id
assignment (worker.py:876 set_visible_accelerator_ids) to TPU natively.

The raylet also supervises the node's object-store daemon and its worker
processes (it is their parent, like the reference's raylet forking language
workers via WorkerPool::StartWorkerProcess).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import debug_locks
from ray_tpu._private.config import config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.rpc import LoopHandle, RpcClient, RpcServer
from ray_tpu.observability import dump as obs_dump
from ray_tpu.observability import events as obs_events

logger = logging.getLogger("ray_tpu.raylet")


# ---------------------------------------------------------------------------
# Resource accounting (reference: src/ray/common/scheduling/
# cluster_resource_data.h ResourceSet/ResourceInstanceSet — TPU chips are
# tracked as instances so leases get concrete chip ids)
# ---------------------------------------------------------------------------
class ResourceSet:
    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self.available = dict(total)
        n_tpu = int(total.get("TPU", 0))
        self.free_tpu_chips: List[int] = list(range(n_tpu))

    def can_fit(self, req: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in req.items())

    def feasible(self, req: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + 1e-9 >= v for k, v in req.items())

    def allocate(self, req: Dict[str, float]) -> Optional[Dict[str, Any]]:
        if not self.can_fit(req):
            return None
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) - v
        chips: List[int] = []
        n = int(req.get("TPU", 0))
        if n > 0:
            chips = self.free_tpu_chips[:n]
            self.free_tpu_chips = self.free_tpu_chips[n:]
        return {"resources": dict(req), "tpu_chips": chips}

    def release(self, alloc: Dict[str, Any]) -> None:
        for k, v in alloc.get("resources", {}).items():
            self.available[k] = min(self.total.get(k, 0.0), self.available.get(k, 0.0) + v)
        chips = alloc.get("tpu_chips", [])
        if chips:
            self.free_tpu_chips.extend(chips)
            self.free_tpu_chips.sort()


class ZygoteProc:
    """Popen-shaped view of a worker forked by the zygote (the zygote,
    not this raylet, is its parent — liveness comes from a pidfd, which
    signals readable once the process exits, zombie included).
    Readiness is checked with select.poll(), NOT select.select(): with
    thousands of workers each holding a pidfd plus sockets, fds exceed
    1023 and select() raises. The no-pidfd fallback pins the process's
    create time so a recycled pid (the zygote reaps promptly) cannot
    impersonate a live worker."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._create_time: Optional[float] = None
        try:
            self._pidfd = os.pidfd_open(pid)
        except (OSError, AttributeError) as e:
            self._pidfd = None
            logger.warning("pidfd_open(%d) failed (%s); falling back to "
                           "create-time liveness probing", pid, e)
            try:
                import psutil

                self._create_time = psutil.Process(pid).create_time()
            except Exception:  # noqa: BLE001 — already gone
                self.returncode = 0

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self._pidfd is not None:
            import select as _select

            p = _select.poll()
            p.register(self._pidfd, _select.POLLIN)
            if not p.poll(0):
                return None
        else:
            try:
                import psutil

                if psutil.Process(self.pid).create_time() == \
                        self._create_time:
                    return None
            except Exception:  # noqa: BLE001 — gone or recycled
                pass
        self.returncode = 0  # exit code unknowable for a non-child
        if self._pidfd is not None:
            try:
                os.close(self._pidfd)
            except OSError:
                pass
            self._pidfd = None
        return self.returncode

    def terminate(self) -> None:
        try:
            os.kill(self.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("zygote-worker", timeout)
            time.sleep(0.02)
        return self.returncode


class Zygote:
    """Client for the prefork worker factory (workers/zygote.py): one
    warmed child process; each spawn request forks it in ~ms instead of
    paying a cold interpreter + import chain per worker."""

    def __init__(self, env: Dict[str, str], session_dir: str):
        self._lock = debug_locks.maybe_wrap(
            threading.Lock(), "raylet.Zygote._lock")
        self._log = open(os.path.join(session_dir, "zygote.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.workers.zygote"],
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=self._log,
        )

    def alive(self) -> bool:
        return self.proc.poll() is None

    def spawn(self, env: Dict[str, str], log_path: str) -> int:
        msg = json.dumps({"env": env, "log_path": log_path}) + "\n"
        with self._lock:
            self.proc.stdin.write(msg.encode())
            self.proc.stdin.flush()
            line = self.proc.stdout.readline()
        if not line:
            raise RuntimeError("zygote exited")
        reply = json.loads(line)
        if "pid" not in reply:
            raise RuntimeError(f"zygote spawn failed: {reply.get('error')}")
        return reply["pid"]

    def stop(self) -> None:
        try:
            self.proc.terminate()
        except Exception:  # noqa: BLE001
            pass
        try:
            self._log.close()
        except Exception:  # noqa: BLE001
            pass


@dataclass
class WorkerHandle:
    worker_id: str
    proc: Any  # subprocess.Popen | ZygoteProc
    addr: Optional[Tuple[str, int]] = None
    registered: asyncio.Event = field(default_factory=asyncio.Event)
    busy_lease: Optional[str] = None
    idle_since: float = field(default_factory=time.monotonic)
    dead: bool = False
    # runtime env this worker is tainted with ("" = clean). A worker
    # that applied env A is never leased for env B (reference: the
    # worker pool dedicates workers per runtime env, worker_pool.h:280)
    env_hash: str = ""


@dataclass
class Lease:
    lease_id: str
    worker: WorkerHandle
    alloc: Dict[str, Any]
    scheduling_class: Any
    job_id: str
    for_actor: Optional[str] = None
    blocked: bool = False  # worker is blocked in get(); CPU released
    cpu_released: bool = False  # actor lease: CPU returned after grant
    granted_at: float = field(default_factory=time.monotonic)


@dataclass
class PendingLease:
    request: dict
    future: asyncio.Future


class Raylet:
    def __init__(
        self,
        node_id: str,
        gcs_addr: Tuple[str, int],
        resources: Dict[str, float],
        store_socket: str,
        store_capacity: int,
        port: int = 0,
        is_head: bool = False,
        labels: Optional[Dict[str, str]] = None,
        session_dir: str = "",
    ):
        self.node_id = node_id
        self.gcs_addr = gcs_addr
        self.resources = ResourceSet(resources)
        self.store_socket = store_socket
        self.store_capacity = store_capacity
        self.is_head = is_head
        self.labels = labels or {}
        self.session_dir = session_dir or tempfile.mkdtemp(prefix="ray_tpu_")
        self.server = RpcServer(port=port, name="raylet")
        self.server.register_instance(self)
        self.gcs: Optional[RpcClient] = None
        self.store_proc: Optional[subprocess.Popen] = None
        self.workers: Dict[str, WorkerHandle] = {}
        self.idle_workers: List[WorkerHandle] = []
        self.leases: Dict[str, Lease] = {}
        self.pending: List[PendingLease] = []
        self.autoscaling_enabled = False
        self._pending_death_notices: List[dict] = []
        self._death_flush_running = False
        # placement group bundles: (pg_id, bundle_index) -> alloc
        self.prepared_bundles: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self.committed_bundles: Dict[Tuple[str, int], "ResourceSet"] = {}
        self._starting_workers = 0
        # worker-pool replenishment: peak concurrent leases over the
        # recent window; after churn (actor kills, OOM reaps) the reap
        # loop respawns idle workers back toward this level so the next
        # burst's leases find warm registered workers instead of paying
        # zygote spawns inside the lease path (reference: WorkerPool
        # prestart-on-demand). Decays to 0 after 30s without a grant.
        self._recent_lease_peak = 0
        self._recent_lease_ts = 0.0
        self._zygote: Optional[Zygote] = None
        self._zygote_lock = threading.Lock()
        self.num_oom_kills = 0
        # single-consumer drain: _drain_pending rebuilds self.pending and
        # must never run reentrantly (two interleaved drains clobber each
        # other's rebuild); callers kick the event instead of calling it
        self._drain_wakeup: Optional[asyncio.Event] = None
        # cluster resource view, refreshed from GCS heartbeat replies
        # (reference: ray_syncer.h:91); drives lease spillback
        self.cluster_view: Dict[str, dict] = {}
        # client to this node's own store daemon, for serving object pulls
        # (reference: object_manager.cc:587 HandlePush / :221 Pull)
        self.store = None
        # in-flight outbound transfers: oid -> {view, last_used, readers};
        # guarded by _pull_pins_lock (touched from executor threads + loop)
        self._pull_pins: Dict[Any, dict] = {}
        self._pull_pins_lock = threading.Lock()
        # Spilling (reference: local_object_manager.h:145 SpillObjects /
        # :157 restore): the store runs no-evict; on pressure this raylet
        # moves LRU sealed+unpinned objects to disk and restores on read.
        # oid_bin -> (path, size); guarded by _spill_lock.
        self.spill_dir = config.object_spilling_dir or os.path.join(
            self.session_dir, "spill"
        )
        self.spilled: Dict[bytes, Tuple[str, int]] = {}
        # _spill_lock guards the `spilled` dict ONLY (held briefly — async
        # handlers touch it on the event loop); _spill_work_lock serializes
        # whole spill/restore batches on executor threads (held across disk
        # IO; reentrant because restore-on-full spills recursively)
        self._spill_lock = threading.Lock()
        self._spill_work_lock = threading.RLock()
        self._spilled_bytes_total = 0
        self._restored_bytes_total = 0
        # freshly restored objects get a short no-respill grace so the
        # reader that asked for the restore can pin them before the next
        # spill round picks them (they are sealed+unpinned+LRU-old)
        self._restore_grace: Dict[bytes, float] = {}
        # graceful drain (reference: NodeManager::HandleDrainRaylet):
        # once draining, no lease is ever granted again; in-flight task
        # leases run out (bounded by the deadline), primary object
        # copies are pushed to a survivor, then this daemon deregisters
        # and exits
        self.draining = False
        self.drain_reason = ""
        self.drain_deadline = 0.0
        self._drain_task: Optional[asyncio.Task] = None
        # inbound drain-pushed objects mid-transfer: oid_bin -> buffer
        self._incoming_objects: Dict[bytes, Any] = {}

    # ------------------------------------------------------------------
    # Worker pool (reference: worker_pool.h:280)
    # ------------------------------------------------------------------
    def _worker_env(self, worker_id: str = "") -> Dict[str, str]:
        env = dict(os.environ)
        if worker_id:
            env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_RAYLET_ADDR"] = f"{self.server.host}:{self.server.port}"
        env["RAY_TPU_GCS_ADDR"] = f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"
        env["RAY_TPU_STORE_SOCKET"] = self.store_socket
        env["RAY_TPU_NODE_ID"] = self.node_id
        env["RAY_TPU_CONFIG_JSON"] = config.to_json()
        # workers must not grab the TPU runtime at import; chips are
        # assigned per-lease via TPU_VISIBLE_CHIPS
        env.setdefault("JAX_PLATFORMS", "")
        return env

    def _get_zygote(self) -> Optional[Zygote]:
        if not config.worker_zygote_enabled:
            return None
        # _spawn_worker runs on executor threads — without the lock a
        # spawn burst would race two Zygote() constructions and orphan
        # one warmed process
        with self._zygote_lock:
            z = self._zygote
            if z is not None and z.alive():
                return z
            if z is not None:
                z.stop()
            try:
                # lazily (re)started: the server port is only known after
                # start, and a crashed zygote must not take the pool down
                self._zygote = Zygote(self._worker_env(), self.session_dir)
            except Exception:  # noqa: BLE001
                logger.exception("zygote start failed; using cold spawns")
                self._zygote = None
            return self._zygote

    def _spawn_worker(self) -> WorkerHandle:
        worker_id = uuid.uuid4().hex
        log_path = os.path.join(self.session_dir, f"worker-{worker_id[:8]}.log")
        proc: Any = None
        zygote = self._get_zygote()
        # spawn instant, on this host's monotonic clock: the worker
        # attaches its age-at-CreateActor to the worker_started mark so
        # timelines can tell a cold fork+boot from a pooled/prestarted
        # worker without trusting a backdated stamp
        spawn_env = {"RAY_TPU_WORKER_ID": worker_id,
                     "RAY_TPU_WORKER_SPAWNED_MONO": repr(time.monotonic())}
        if zygote is not None:
            try:
                pid = zygote.spawn(spawn_env, log_path)
                proc = ZygoteProc(pid)
            except Exception:  # noqa: BLE001
                logger.exception("zygote spawn failed; cold spawn instead")
        if proc is None:
            env = self._worker_env(worker_id)
            env.update(spawn_env)
            with open(log_path, "ab") as logf:
                proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "ray_tpu._private.workers.default_worker"],
                    env=env,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                )
        handle = WorkerHandle(worker_id=worker_id, proc=proc)
        self.workers[worker_id] = handle
        return handle

    async def PrestartWorkers(self, count: int = 1) -> dict:
        """Ensure up to ``count`` spare workers are idle or starting
        (reference: WorkerPool::PrestartWorkers). The GCS fires this
        when a burst of PENDING actors queues at its creation gates, and
        the reap loop fires it to replenish after churn — zygote spawns
        then overlap the gated lease+CreateActor pipelines instead of
        running inside them; each spawned worker parks in the idle pool
        on registration and the next lease request grants instantly."""
        if self.draining:
            return {"started": 0}
        supply = len(self.idle_workers) + self._starting_workers
        room = (config.max_workers_per_node - len(self.workers)
                - self._starting_workers)
        spawn = min(max(0, int(count)) - supply, room)
        started = 0
        loop = asyncio.get_event_loop()
        for _ in range(max(0, spawn)):
            self._starting_workers += 1
            started += 1

            async def _boot():
                try:
                    handle = await loop.run_in_executor(
                        None, self._spawn_worker)
                    try:
                        await asyncio.wait_for(
                            handle.registered.wait(),
                            timeout=config.worker_startup_timeout_s)
                    except asyncio.TimeoutError:
                        handle.dead = True
                        handle.proc.kill()
                        self.workers.pop(handle.worker_id, None)
                        return
                    handle.idle_since = time.monotonic()
                    self.idle_workers.append(handle)
                    self._kick_drain()
                except Exception:  # noqa: BLE001 — prestart is advisory
                    logger.exception("prestart spawn failed")
                finally:
                    self._starting_workers -= 1

            asyncio.ensure_future(_boot())
        return {"started": started}

    async def RegisterWorker(self, worker_id: str, addr: Tuple[str, int]) -> dict:
        handle = self.workers.get(worker_id)
        if handle is None:
            return {"ok": False}
        handle.addr = tuple(addr)
        handle.registered.set()
        logger.info("worker %s registered at %s", worker_id[:8], addr)
        return {"ok": True, "node_id": self.node_id}

    async def _get_idle_worker(self, env_hash: str = "") -> Optional[WorkerHandle]:
        # prefer a worker already tainted with THIS env, then a clean
        # one (which the env will taint); never cross-match envs
        match = None
        for w in reversed(self.idle_workers):
            if w.dead or w.proc.poll() is not None:
                continue
            if w.env_hash == env_hash:
                match = w
                break
            if match is None and not w.env_hash:
                match = w
        if match is not None:
            self.idle_workers.remove(match)
            match.env_hash = env_hash or match.env_hash
            # drop any dead entries we skipped over
            self.idle_workers = [
                w for w in self.idle_workers
                if not w.dead and w.proc.poll() is None
            ]
            return match
        self.idle_workers = [
            w for w in self.idle_workers
            if not w.dead and w.proc.poll() is None
        ]
        if len(self.workers) + self._starting_workers >= config.max_workers_per_node:
            if not self.idle_workers:
                return None
            # at the cap with only env-mismatched idle workers: evict one
            # to make room (reference: the worker pool kills idle workers
            # of other envs rather than starving the request)
            victim = self.idle_workers.pop(0)
            victim.dead = True
            self.workers.pop(victim.worker_id, None)
            try:
                victim.proc.terminate()
            except Exception:  # noqa: BLE001
                pass
            logger.info(
                "evicted idle worker %s (env %s) to serve a different env",
                victim.worker_id[:8], victim.env_hash[:8] or "<clean>")
        self._starting_workers += 1
        try:
            # executor thread: a zygote boot (first spawn) or a cold
            # Popen must not stall the raylet's event loop
            handle = await asyncio.get_event_loop().run_in_executor(
                None, self._spawn_worker)
            logger.debug("spawning worker %s (pid %s)", handle.worker_id[:8], handle.proc.pid)
            try:
                await asyncio.wait_for(
                    handle.registered.wait(), timeout=config.worker_startup_timeout_s
                )
            except asyncio.TimeoutError:
                logger.error(
                    "worker %s failed to register in time (proc poll=%s)",
                    handle.worker_id[:8],
                    handle.proc.poll(),
                )
                handle.dead = True
                handle.proc.kill()
                self.workers.pop(handle.worker_id, None)
                return None
            handle.env_hash = env_hash
            return handle
        finally:
            self._starting_workers -= 1

    # ------------------------------------------------------------------
    # Lease protocol (reference: node_manager.cc:1834 HandleRequestWorkerLease,
    # cluster_lease_manager.cc queue/grant)
    # ------------------------------------------------------------------
    async def RequestWorkerLease(
        self,
        resources: Dict[str, float],
        scheduling_class: Any,
        job_id: str,
        for_actor: Optional[str] = None,
        pg_id: Optional[str] = None,
        bundle_index: int = -1,
        lease_timeout: float = 25.0,
        release_cpu_after_grant: bool = False,
        allow_spillback: bool = True,
        hard_node_constraint: str = "",
        runtime_env_hash: str = "",
    ) -> dict:
        if self.draining:
            # a draining node grants nothing new; the redirect (when a
            # survivor exists) lets the caller re-lease in one hop, and
            # the caller's drain-aware retry never burns max_retries on it
            return self._draining_reply(resources, pg_id=pg_id,
                                        hard_node_constraint=hard_node_constraint)
        req = {
            "resources": dict(resources),
            "scheduling_class": scheduling_class,
            "job_id": job_id,
            "for_actor": for_actor,
            "pg_id": pg_id,
            "bundle_index": bundle_index,
            "release_cpu_after_grant": release_cpu_after_grant,
            "runtime_env_hash": runtime_env_hash,
            # "pinned" (hard NodeAffinity) / "labeled" (hard NodeLabel):
            # the lease must run HERE — distinct from allow_spillback=False
            # alone, which also marks already-spilled requests (loop
            # prevention) that may still be redirected. A pinned lease that
            # can't fit is infeasible outright; a labeled one may be served
            # by another matching or autoscaled node after caller retry.
            "hard_node_constraint": hard_node_constraint,
        }
        logger.debug(
            "lease request %s avail=%s idle=%d workers=%d",
            resources,
            self.resources.available,
            len(self.idle_workers),
            len(self.workers),
        )
        grant = await self._try_grant(req)
        if grant is not None:
            return grant
        rs, _ = self._resource_set_for(req)
        # Spillback (reference: cluster_lease_manager.cc:420): the local node
        # can't serve the request right now — redirect the caller to a node
        # that can. Never for PG leases (bundles are node-pinned), and a
        # spilled request can't spill again (loop prevention).
        if allow_spillback and not pg_id:
            if not rs.feasible(req["resources"]):
                # can NEVER run here: any node whose totals fit will do
                target = self._pick_spillback(req["resources"], require_available=False)
            elif not rs.can_fit(req["resources"]):
                # feasible but saturated: spill only to a node with capacity now
                target = self._pick_spillback(req["resources"], require_available=True)
            else:
                target = None  # local can serve (worker may still be spawning)
            if target is not None:
                return {"granted": False, "spillback": target}
        if not rs.feasible(self._cpu_only(req["resources"], pg_id)):
            if hard_node_constraint == "pinned":
                # pinned to THIS node and can never fit here: no spillback,
                # and no autoscaled node can ever serve it — fail now
                return self._infeasible_reply(req["resources"], rs)
            if hard_node_constraint == "labeled" and \
                    not self.autoscaling_enabled:
                # the caller already picked the best label match; with no
                # autoscaler a bigger matching node will never appear
                return self._infeasible_reply(req["resources"], rs)
            if allow_spillback and not pg_id:
                # The cluster view may be a couple of heartbeats behind (a
                # just-joined node propagates via its heartbeat to GCS, then
                # ours). Wait ~2 periods with a populated view, longer when
                # the raylet just started and has no view at all.
                hb = config.raylet_heartbeat_period_ms / 1000.0
                grace = max(1.0, 2 * hb) if self.cluster_view else max(1.0, 4 * hb)
                target = await self._await_spillback(req["resources"], grace)
                if target is not None:
                    return {"granted": False, "spillback": target}
            if not self.autoscaling_enabled:
                return self._infeasible_reply(resources, rs)
            # An attached autoscaler may add a node that fits: queue the
            # request so its shape shows up as demand in heartbeats
            # (reference: infeasible tasks wait for the autoscaler); the
            # caller's retry-after-timeout picks up the new node via
            # spillback.
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        pl = PendingLease(req, fut)
        self.pending.append(pl)
        try:
            return await asyncio.wait_for(fut, timeout=lease_timeout)
        except asyncio.TimeoutError:
            try:
                self.pending.remove(pl)
            except ValueError:
                pass
            return {"granted": False, "infeasible": False, "error": "lease wait timed out"}

    def _cpu_only(self, resources: Dict[str, float], pg_id: Optional[str]) -> Dict[str, float]:
        return dict(resources)

    @staticmethod
    def _infeasible_reply(resources: Dict[str, float], rs) -> dict:
        return {
            "granted": False,
            "infeasible": True,
            "error": f"resources {resources} can never be satisfied on "
            f"this node (total: {rs.total})",
        }

    async def _await_spillback(
        self, resources: Dict[str, float], timeout_s: float
    ) -> Optional[Tuple[str, int]]:
        """Poll the heartbeat-synced cluster view for a node whose totals fit
        a locally-infeasible request (covers view staleness at startup and
        nodes that just joined)."""
        deadline = time.monotonic() + timeout_s
        while True:
            target = self._pick_spillback(resources, require_available=False)
            if target is not None:
                return target
            if time.monotonic() >= deadline:
                return None
            await asyncio.sleep(0.1)

    def _pick_spillback(
        self, resources: Dict[str, float], require_available: bool
    ) -> Optional[Tuple[str, int]]:
        """Pick another node's raylet for lease spillback: rank candidates
        by availability, then choose RANDOMLY among the top-k (reference:
        hybrid_scheduling_policy.h:29-46 — the top-k jitter stops every
        node in the cluster from herding onto one 'best' target)."""
        import random as _random

        candidates = []
        for nid, info in self.cluster_view.items():
            if nid == self.node_id or not info.get("alive") \
                    or info.get("draining"):
                continue
            total = info.get("total", {})
            avail = info.get("available", {})
            if not all(total.get(k, 0.0) + 1e-9 >= v for k, v in resources.items()):
                continue
            has_now = all(avail.get(k, 0.0) + 1e-9 >= v for k, v in resources.items())
            if require_available and not has_now:
                continue
            score = (1 if has_now else 0, avail.get("CPU", 0.0))
            candidates.append((score, tuple(info["addr"])))
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[0], reverse=True)
        k = max(config.scheduler_top_k_absolute,
                int(len(candidates) * config.scheduler_top_k_fraction))
        return _random.choice(candidates[:max(1, k)])[1]

    def _resource_set_for(self, req: dict) -> Tuple[ResourceSet, Optional[Tuple[str, int]]]:
        """Returns (resource_set, committed_bundle_key). The key is the
        RESOLVED bundle (never index -1) so release finds the same set."""
        pg_id = req.get("pg_id")
        if pg_id:
            key = (pg_id, req.get("bundle_index", -1))
            if key in self.committed_bundles:
                return self.committed_bundles[key], key
            # bundle_index -1: any committed bundle of that pg with room
            for (p, idx), rs in self.committed_bundles.items():
                if p == pg_id and rs.can_fit(req["resources"]):
                    return rs, (p, idx)
            for (p, idx), rs in self.committed_bundles.items():
                if p == pg_id:
                    return rs, (p, idx)
        return self.resources, None

    async def _try_grant(self, req: dict) -> Optional[dict]:
        rs, pg_key = self._resource_set_for(req)
        # allocate BEFORE any await: resource accounting is what bounds
        # concurrent lease grants (and worker spawns) on this node
        alloc = rs.allocate(req["resources"])
        if alloc is None:
            return None
        worker = await self._get_idle_worker(req.get("runtime_env_hash") or "")
        if worker is None:
            rs.release(alloc)
            return None
        alloc["from_pg"] = pg_key
        lease_id = uuid.uuid4().hex
        lease = Lease(
            lease_id=lease_id,
            worker=worker,
            alloc=alloc,
            scheduling_class=req["scheduling_class"],
            job_id=req["job_id"],
            for_actor=req.get("for_actor"),
        )
        worker.busy_lease = lease_id
        self.leases[lease_id] = lease
        now = time.monotonic()
        if len(self.leases) >= self._recent_lease_peak:
            self._recent_lease_peak = len(self.leases)
        self._recent_lease_ts = now
        logger.debug("granting lease %s to worker %s (avail now %s)", lease_id[:8], worker.worker_id[:8], rs.available)
        # configure the leased worker's visible TPU chips. The client
        # binds to THIS loop (LoopHandle): the SetLeaseContext roundtrip
        # runs in-line on the raylet's own event loop instead of hopping
        # threads to the global client loop and back.
        wclient = RpcClient(worker.addr[0], worker.addr[1],
                            self._loop_handle())
        try:
            await wclient.acall(
                "SetLeaseContext",
                lease_id=lease_id,
                tpu_chips=alloc["tpu_chips"],
                resources=alloc["resources"],
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("failed to set lease context on worker: %s", e)
            self._release_lease(lease, worker_dead=True)
            return None
        finally:
            # close on the failure path too — one leaked RpcClient per
            # failed SetLeaseContext pins a socket and read-loop task
            # (RC006)
            wclient.close()
        if req.get("release_cpu_after_grant"):
            # actor with defaulted num_cpus: CPU was only a scheduling
            # requirement — hand it back so long-lived actors don't starve
            # task leases (reference: actors hold 0 CPU while alive)
            cpu = alloc["resources"].get("CPU", 0.0)
            if cpu:
                lease.cpu_released = True
                rs.available["CPU"] = rs.available.get("CPU", 0.0) + cpu
                self._kick_drain()
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_addr": worker.addr,
            "worker_id": worker.worker_id,
            "tpu_chips": alloc["tpu_chips"],
        }

    def _release_lease(self, lease: Lease, worker_dead: bool) -> None:
        rs = self._rs_for_lease(lease)
        alloc = lease.alloc
        if lease.blocked or lease.cpu_released:
            # the CPU share was already released (worker blocked in get(),
            # or an actor lease that only used CPU for scheduling)
            res = dict(alloc["resources"])
            res.pop("CPU", None)
            alloc = dict(alloc, resources=res)
        rs.release(alloc)
        self.leases.pop(lease.lease_id, None)
        w = lease.worker
        w.busy_lease = None
        if worker_dead or w.proc.poll() is not None:
            w.dead = True
            self.workers.pop(w.worker_id, None)
            try:
                w.proc.kill()
            except Exception:
                pass
        else:
            w.idle_since = time.monotonic()
            self.idle_workers.append(w)

    async def NotifyWorkerBlocked(self, lease_id: str) -> dict:
        """Worker is blocked in get() waiting on objects: temporarily release
        its CPU so dependents can run (reference: NodeManager::
        HandleNotifyDirectCallTaskBlocked, src/ray/raylet/node_manager.cc —
        prevents nested-task deadlock). TPU chips stay assigned."""
        lease = self.leases.get(lease_id)
        if lease is not None and not lease.blocked:
            lease.blocked = True
            cpu = lease.alloc["resources"].get("CPU", 0.0)
            if cpu and not lease.cpu_released:
                rs = self._rs_for_lease(lease)
                rs.available["CPU"] = rs.available.get("CPU", 0.0) + cpu
            self._kick_drain()
        return {"ok": True}

    async def NotifyWorkerUnblocked(self, lease_id: str) -> dict:
        lease = self.leases.get(lease_id)
        if lease is not None and lease.blocked:
            lease.blocked = False
            cpu = lease.alloc["resources"].get("CPU", 0.0)
            if cpu and not lease.cpu_released:
                # may go negative: transient oversubscription, like the
                # reference's cpu-borrowing on unblock
                rs = self._rs_for_lease(lease)
                rs.available["CPU"] = rs.available.get("CPU", 0.0) - cpu
        return {"ok": True}

    def _rs_for_lease(self, lease: Lease) -> ResourceSet:
        if lease.alloc.get("from_pg"):
            return self.committed_bundles.get(tuple(lease.alloc["from_pg"]), self.resources)
        return self.resources

    async def ReturnWorkerLease(self, lease_id: str, worker_dead: bool = False) -> dict:
        lease = self.leases.get(lease_id)
        logger.debug("return lease %s (found=%s, dead=%s)", lease_id[:8], lease is not None, worker_dead)
        if lease is None:
            return {"ok": False}
        self._release_lease(lease, worker_dead)
        self._kick_drain()
        return {"ok": True}

    def _undo_grant(self, grant: dict) -> None:
        """Roll back a grant whose requester vanished (timed-out future)."""
        lease = self.leases.get(grant["lease_id"])
        if lease is not None:
            self._release_lease(lease, worker_dead=False)

    def _kick_drain(self) -> None:
        if self._drain_wakeup is not None:
            self._drain_wakeup.set()

    async def _drain_loop(self) -> None:
        """Sole consumer of self.pending — see _drain_wakeup comment."""
        self._drain_wakeup = asyncio.Event()
        while True:
            try:
                await asyncio.wait_for(self._drain_wakeup.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass
            self._drain_wakeup.clear()
            try:
                await self._drain_pending()
            except Exception:  # noqa: BLE001
                logger.exception("pending-lease drain failed")

    async def _drain_pending(self) -> None:
        still: List[PendingLease] = []
        for p in self.pending:
            if p.future.done():
                continue
            grant = await self._try_grant(p.request)
            if grant is None:
                # a queued request this node can NEVER serve (it sits here
                # as autoscaler demand) redirects the moment a fitting node
                # appears in the cluster view — without this, the caller
                # only reaches a fresh node after its full lease timeout,
                # and the autoscaler sees the new node as idle and kills
                # it (scale-up/terminate flapping)
                rs, _ = self._resource_set_for(p.request)
                if not p.request.get("pg_id") and \
                        not rs.feasible(p.request["resources"]):
                    # a hard node constraint must never be redirected
                    # elsewhere (spilled requests — allow_spillback=False
                    # without the constraint — may still be re-redirected):
                    # pinned fails precisely; labeled stays queued as
                    # autoscaler demand until the caller's timeout retry
                    # re-picks among (possibly new) matching nodes
                    hard = p.request.get("hard_node_constraint")
                    if hard == "pinned":
                        if not p.future.done():
                            try:
                                p.future.set_result(self._infeasible_reply(
                                    p.request["resources"], rs))
                            except asyncio.InvalidStateError:
                                pass
                        continue
                    if hard == "labeled":
                        still.append(p)
                        continue
                    target = self._pick_spillback(
                        p.request["resources"], require_available=False)
                    if target is not None and not p.future.done():
                        try:
                            p.future.set_result(
                                {"granted": False, "spillback": target})
                        except asyncio.InvalidStateError:
                            pass
                        continue
                still.append(p)
                continue
            # the future may have been cancelled (requester timeout) while
            # _try_grant awaited worker startup — undo, don't leak the lease
            if p.future.done():
                self._undo_grant(grant)
                continue
            try:
                p.future.set_result(grant)
            except asyncio.InvalidStateError:
                self._undo_grant(grant)
        self.pending = [p for p in still if not p.future.done()]

    # ------------------------------------------------------------------
    # Placement group bundles (reference: placement_group_resource_manager.h
    # 2PC prepare/commit/cancel/release)
    # ------------------------------------------------------------------
    async def PrepareBundle(self, pg_id: str, bundle_index: int, resources: Dict[str, float]) -> dict:
        alloc = self.resources.allocate(resources)
        if alloc is None:
            return {"ok": False, "error": "insufficient resources"}
        self.prepared_bundles[(pg_id, bundle_index)] = alloc
        return {"ok": True}

    async def CommitBundle(self, pg_id: str, bundle_index: int) -> dict:
        alloc = self.prepared_bundles.pop((pg_id, bundle_index), None)
        if alloc is None:
            return {"ok": False}
        total = dict(alloc["resources"])
        rs = ResourceSet(total)
        # bundle inherits concrete chips reserved from the node
        rs.free_tpu_chips = list(alloc.get("tpu_chips", []))
        rs._node_alloc = alloc  # keep to release back later
        self.committed_bundles[(pg_id, bundle_index)] = rs
        return {"ok": True}

    async def CancelBundle(self, pg_id: str, bundle_index: int) -> dict:
        alloc = self.prepared_bundles.pop((pg_id, bundle_index), None)
        if alloc is not None:
            self.resources.release(alloc)
        return {"ok": True}

    async def ReleaseBundle(self, pg_id: str, bundle_index: int) -> dict:
        rs = self.committed_bundles.pop((pg_id, bundle_index), None)
        if rs is not None and hasattr(rs, "_node_alloc"):
            self.resources.release(rs._node_alloc)
        self._kick_drain()
        return {"ok": True}

    # ------------------------------------------------------------------
    # Graceful drain (reference: NodeManager::HandleDrainRaylet +
    # local_object_manager eviction-before-death; _private/drain.py has
    # the cluster-wide lifecycle)
    # ------------------------------------------------------------------
    async def Drain(self, reason: str = "",
                    deadline_s: Optional[float] = None) -> dict:
        if self.draining:
            return {"ok": True, "already": True}
        if deadline_s is None:
            deadline_s = config.drain_deadline_default_s
        self.draining = True
        self.drain_reason = reason
        self.drain_deadline = time.monotonic() + max(0.0, deadline_s)
        logger.info("draining (%s, deadline %.1fs): %d lease(s) in "
                    "flight, %d pending", reason, deadline_s,
                    len(self.leases), len(self.pending))
        # queued lease requests will never be granted here: answer them
        # NOW with a redirect so their callers re-lease elsewhere instead
        # of burning their full wait timeout against a dying node
        pending, self.pending = self.pending, []
        for p in pending:
            if p.future.done():
                continue
            try:
                p.future.set_result(self._draining_reply(
                    p.request.get("resources") or {},
                    pg_id=p.request.get("pg_id"),
                    hard_node_constraint=p.request.get(
                        "hard_node_constraint", "")))
            except asyncio.InvalidStateError:
                pass
        self._drain_task = asyncio.ensure_future(
            self._drain_task_run())
        return {"ok": True}

    def _draining_reply(self, resources: Dict[str, float],
                        pg_id: Optional[str] = None,
                        hard_node_constraint: str = "") -> dict:
        """Lease rejection for a draining node: carries a spillback
        target when one exists so the caller's existing redirect path
        re-leases elsewhere in one hop. PG-bundle and hard-constrained
        requests (pinned NodeAffinity AND hard NodeLabel) are NEVER
        redirected — the spillback picker filters on resources only, so
        a redirect could land them on a node violating the constraint;
        the normal path never spills them either. Their callers
        retry/fail through the placement machinery instead."""
        reply = {"granted": False, "draining": True,
                 "error": "node is draining"}
        if not pg_id and not hard_node_constraint:
            target = self._pick_spillback(resources,
                                          require_available=False)
            if target is not None:
                reply["spillback"] = target
        return reply

    async def _drain_task_run(self) -> None:
        from ray_tpu._private import drain as drain_mod

        # 0) recall warm leases: tell every worker to refuse further
        # task pushes (node_draining reply) — the callers holding
        # keepalive-cached leases return them and re-lease elsewhere,
        # so a sustained task stream doesn't pin its lease here for the
        # whole deadline (and then die mid-task at the kill)
        async def _notify(w: WorkerHandle) -> None:
            if w.addr is None or w.dead:
                return
            c = RpcClient(w.addr[0], w.addr[1], self._loop_handle())
            try:
                await c.acall("NotifyNodeDraining", timeout=5)
            except Exception:  # noqa: BLE001 — worker already gone
                pass
            finally:
                c.close()

        await asyncio.gather(
            *(_notify(w) for w in list(self.workers.values())),
            return_exceptions=True)
        # 1) let in-flight TASK leases run out (actor leases are
        # migrated by the GCS in parallel — their workers are torn down
        # at exit below). Idle warm leases held by callers come back via
        # their keepalive sweepers within worker_lease_keepalive_s.
        while time.monotonic() < self.drain_deadline:
            task_leases = [l for l in self.leases.values()
                           if not l.for_actor]
            if not task_leases:
                break
            await asyncio.sleep(0.05)
        # 2) push primary object copies to a surviving node so borrowed
        # refs outlive this node (skipped on whole-cluster shutdown —
        # there is nobody left to read them)
        moved: Dict[bytes, str] = {}
        if self.drain_reason != drain_mod.REASON_CLUSTER_SHUTDOWN:
            target = self._pick_drain_target()
            if target is not None:
                loop = asyncio.get_event_loop()
                try:
                    moved = await loop.run_in_executor(
                        None, self._push_objects_sync, target)
                except Exception:  # noqa: BLE001
                    logger.exception("drain object push failed")
        # 3) confirm to the GCS (it finishes actor migration before
        # replying, so worker teardown below cannot race a DrainActor),
        # then deregister by exiting cleanly
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            try:
                await self.gcs.acall(
                    "NodeDrainComplete", node_id=self.node_id,
                    moved_objects=moved, timeout=40)
                break
            except Exception as e:  # noqa: BLE001 — GCS restarting;
                # its heartbeat-relearned DRAINING state + watchdog
                # cover a confirmation that never lands
                logger.warning("NodeDrainComplete failed: %s", e)
                await asyncio.sleep(1.0)
        logger.info("drain complete; raylet exiting")
        self.shutdown_procs()
        # give the log line and any in-flight response frames a beat
        asyncio.get_event_loop().call_later(0.2, os._exit, 0)

    def _pick_drain_target(self) -> Optional[Tuple[str, int]]:
        """A surviving (alive, not draining) node's raylet address."""
        best = None
        for nid, info in self.cluster_view.items():
            if nid == self.node_id or not info.get("alive") \
                    or info.get("draining"):
                continue
            mem = info.get("available", {}).get("memory", 0.0)
            if best is None or mem > best[0]:
                best = (mem, tuple(info["addr"]), nid)
        if best is None:
            return None
        self._drain_target_node_id = best[2]
        return best[1]

    def _push_objects_sync(self, target: Tuple[str, int]) -> Dict[bytes, str]:
        """Push every sealed primary copy (in-memory and spilled) to the
        target raylet's store, chunked. Runs on an executor thread;
        returns oid_bin -> destination node id for the GCS directory."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.rpc import get_client

        client = get_client(target)
        target_nid = getattr(self, "_drain_target_node_id", "")
        chunk = config.object_pull_chunk_bytes
        moved: Dict[bytes, str] = {}

        def _send(oid_bin: bytes, total: int, read) -> bool:
            off = 0
            while off < total or off == 0:
                data = read(off, min(chunk, total - off))
                if data is None:
                    return False
                rep = client.call(
                    "ReceiveObjectChunk", object_id_bin=oid_bin,
                    offset=off, total=total, data=data, timeout=60)
                if rep.get("status") == "exists":
                    return True  # already there (e.g. a reader pulled it)
                if rep.get("status") != "ok":
                    return False
                off += max(1, len(data))
                if total == 0:
                    break
            return True

        try:
            candidates = self.store.list_objects()
        except Exception:  # noqa: BLE001
            candidates = []
        for oid_bin, size, sealed, _pinned in candidates:
            if not sealed:
                continue
            oid = ObjectID(oid_bin)
            [view] = self.store.get([oid], timeout_ms=0)
            if view is None:
                continue
            try:
                if _send(bytes(oid_bin), len(view),
                         lambda o, n, v=view: bytes(v[o:o + n])):
                    moved[bytes(oid_bin)] = target_nid
            except Exception:  # noqa: BLE001 — best effort per object
                pass
            finally:
                try:
                    self.store.release(oid)
                except Exception:  # noqa: BLE001
                    pass
        with self._spill_lock:
            spilled = dict(self.spilled)
        for oid_bin, (path, size) in spilled.items():
            def _read_file(off, n, path=path):
                try:
                    with open(path, "rb") as f:
                        f.seek(off)
                        return f.read(n)
                except OSError:
                    return None
            try:
                if _send(bytes(oid_bin), size, _read_file):
                    moved[bytes(oid_bin)] = target_nid
            except Exception:  # noqa: BLE001
                pass
        if moved:
            logger.info("drain pushed %d primary object(s) to %s",
                        len(moved), target_nid[:12])
        return moved

    async def ReceiveObjectChunk(self, object_id_bin: bytes, offset: int,
                                 total: int, data: bytes) -> dict:
        """Destination side of the drain push: write the chunk into this
        node's store (Create at offset 0, Seal on the last chunk)."""
        from ray_tpu._private.ids import ObjectID

        oid_bin = bytes(object_id_bin)
        oid = ObjectID(oid_bin)
        loop = asyncio.get_event_loop()

        def _write() -> str:
            ent = self._incoming_objects.get(oid_bin)
            if ent is None:
                if offset != 0:
                    return "bad_offset"
                try:
                    if self.store.contains(oid):
                        return "exists"
                    buf = self.store.create(oid, total)
                except FileExistsError:
                    return "exists"
                except Exception:  # noqa: BLE001 — store full
                    self._spill_until(total)
                    try:
                        buf = self.store.create(oid, total)
                    except Exception:  # noqa: BLE001
                        return "full"
                ent = self._incoming_objects[oid_bin] = {
                    "buf": buf, "last_used": time.monotonic()}
            buf = ent["buf"]
            ent["last_used"] = time.monotonic()
            if data:
                buf.data[offset:offset + len(data)] = data
            if offset + len(data) >= total:
                buf.seal()
                del self._incoming_objects[oid_bin]
            return "ok"

        status = await loop.run_in_executor(None, _write)
        return {"status": status}

    # ------------------------------------------------------------------
    # Object manager: serve chunked pulls from this node's store to other
    # nodes (reference: src/ray/object_manager/object_manager.cc:221 Pull,
    # :587 HandlePush — ours is pull-based: the reader drives the transfer)
    # ------------------------------------------------------------------
    async def PullObjectChunk(
        self, object_id_bin: bytes, offset: int = 0, length: int = 0
    ) -> dict:
        from ray_tpu._private.ids import ObjectID

        if self.store is None:
            return {"status": "not_found"}
        oid = ObjectID(object_id_bin)
        loop = asyncio.get_event_loop()

        def _read():
            # pin across the whole multi-chunk transfer: a get-pin is taken
            # when the first reader starts and held in _pull_pins until the
            # LAST concurrent reader finishes (or the idle sweeper fires) —
            # otherwise the store could LRU-evict the object mid-transfer
            with self._pull_pins_lock:
                pinned = self._pull_pins.get(oid)
                if pinned is not None:
                    if offset == 0:
                        pinned["readers"] += 1
                    pinned["last_used"] = time.monotonic()
            if pinned is None:
                [view] = self.store.get([oid], timeout_ms=100)
                if view is None:
                    return None
                extra_pin = False
                with self._pull_pins_lock:
                    existing = self._pull_pins.get(oid)
                    if existing is None:
                        pinned = self._pull_pins[oid] = {
                            "view": view, "last_used": time.monotonic(), "readers": 1,
                        }
                    else:  # lost the creation race: drop our extra store pin
                        pinned = existing
                        if offset == 0:
                            pinned["readers"] += 1
                        extra_pin = True
                if extra_pin:
                    try:
                        self.store.release(oid)
                    except Exception:  # noqa: BLE001
                        pass
            view = pinned["view"]
            total = len(view)
            end = min(total, offset + (length or total))
            data = bytes(view[offset:end])
            if end >= total:
                done = False
                with self._pull_pins_lock:
                    pinned["readers"] -= 1
                    if pinned["readers"] <= 0 and self._pull_pins.get(oid) is pinned:
                        del self._pull_pins[oid]
                        done = True
                if done:
                    try:
                        self.store.release(oid)
                    except Exception:  # noqa: BLE001
                        pass
            return total, data

        res = await loop.run_in_executor(None, _read)
        if res is None:
            # spilled objects are served straight from their file — no
            # need to re-pressure shared memory for an outbound transfer
            res = await loop.run_in_executor(
                None, self._read_spilled_chunk, bytes(object_id_bin), offset, length
            )
        if res is None:
            return {"status": "not_found"}
        total, data = res
        return {"status": "ok", "total": total, "data": data}

    async def ContainsObject(self, object_id_bin: bytes) -> dict:
        """Cheap liveness probe for an object in this node's store (used by
        owners verifying a loss report before reconstructing). Spilled
        objects count: they are on this node, just on disk."""
        from ray_tpu._private.ids import ObjectID

        if self.store is None:
            return {"contains": False}
        with self._spill_lock:
            if object_id_bin in self.spilled:
                return {"contains": True}
        oid = ObjectID(object_id_bin)
        loop = asyncio.get_event_loop()
        found = await loop.run_in_executor(None, lambda: self.store.contains(oid))
        return {"contains": bool(found)}

    # ------------------------------------------------------------------
    # Spilling (reference: src/ray/raylet/local_object_manager.h:145
    # SpillObjectsOfSize / :157 AsyncRestoreSpilledObject)
    # ------------------------------------------------------------------
    def _spill_path(self, oid_bin: bytes) -> str:
        return os.path.join(self.spill_dir, oid_bin.hex())

    def _spill_until(self, needed_bytes: int) -> int:
        """Move LRU sealed+unpinned objects to disk until ~needed_bytes are
        freed. Runs on an executor thread; batches serialize on
        _spill_work_lock (never held on the event loop)."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_store.client import ST_OK

        freed = 0
        with self._spill_work_lock:
            try:
                candidates = self.store.list_objects()
            except Exception:  # noqa: BLE001
                return 0
            with self._pull_pins_lock:
                transferring = set(self._pull_pins)
            now = time.monotonic()
            self._restore_grace = {
                k: t for k, t in self._restore_grace.items() if now - t < 10.0
            }
            for oid_bin, size, sealed, pinned in candidates:
                if freed >= needed_bytes:
                    break
                if not sealed or pinned:
                    continue
                if oid_bin in self._restore_grace:
                    continue  # just restored for a reader; let it pin first
                oid = ObjectID(oid_bin)
                if oid in transferring:
                    continue
                [view] = self.store.get([oid], timeout_ms=0)
                if view is None:
                    continue
                path = self._spill_path(oid_bin)
                try:
                    with open(path, "wb") as f:
                        f.write(view)
                finally:
                    self.store.release(oid)
                status = self.store.delete(oid)
                with self._spill_lock:
                    self.spilled[oid_bin] = (path, size)
                self._spilled_bytes_total += size
                if status == ST_OK:
                    # a pinned-between-list-and-delete object has
                    # pending_delete set and frees memory on last release;
                    # don't count bytes that aren't actually free yet
                    freed += size
            if freed:
                logger.info("spilled %d bytes to %s", freed, self.spill_dir)
        return freed

    async def SpillObjects(self, needed_bytes: int) -> dict:
        """Create backpressure: a client whose create got FULL asks us to
        make room (reference: plasma/create_request_queue.h — ours is
        client-driven retry over raylet-driven spill)."""
        loop = asyncio.get_event_loop()
        freed = await loop.run_in_executor(None, self._spill_until, int(needed_bytes))
        return {"freed": freed}

    def _restore_sync(self, oid_bin: bytes) -> str:
        """Bring a spilled object back into shared memory. Returns
        "ok" | "absent" | "full"."""
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(oid_bin)
        with self._spill_work_lock:
            with self._spill_lock:
                ent = self.spilled.get(oid_bin)
            if ent is None:
                return "absent"
            path, size = ent
            for attempt in range(2):
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except FileNotFoundError:
                    return "absent"
                try:
                    buf = self.store.create(oid, len(data))
                except FileExistsError:
                    break  # concurrent restore won
                except Exception:  # noqa: BLE001 — FULL: spill others, retry
                    if attempt == 0:
                        self._spill_until(len(data))
                        continue
                    return "full"
                buf.data[:] = data
                buf.seal()
                break
            with self._spill_lock:
                still = self.spilled.pop(oid_bin, None)
            if still is None:
                # the owner freed the object mid-restore: don't resurrect
                # an orphan in a store that never evicts
                self.store.delete(oid)
                return "absent"
            self._restored_bytes_total += size
            self._restore_grace[oid_bin] = time.monotonic()
            try:
                os.unlink(path)
            except OSError:
                pass
            return "ok"

    async def RestoreObject(self, object_id_bin: bytes) -> dict:
        loop = asyncio.get_event_loop()
        status = await loop.run_in_executor(None, self._restore_sync, bytes(object_id_bin))
        return {"status": status}

    def _read_spilled_chunk(self, oid_bin: bytes, offset: int, length: int):
        with self._spill_lock:
            ent = self.spilled.get(oid_bin)
        if ent is None:
            return None
        path, size = ent
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(length or size)
        except OSError:
            return None
        return size, data

    async def _pull_pin_sweeper_loop(self) -> None:
        """Release transfer pins whose readers died mid-pull, and abort
        inbound drain-pushed buffers whose sender died mid-transfer (a
        hard-killed draining node must not leak an unsealed allocation
        on the survivor forever)."""
        while True:
            await asyncio.sleep(10)
            cutoff = time.monotonic() - 60
            stale = []
            with self._pull_pins_lock:
                for oid, pinned in list(self._pull_pins.items()):
                    if pinned["last_used"] < cutoff:
                        del self._pull_pins[oid]
                        stale.append(oid)
            for oid in stale:
                try:
                    self.store.release(oid)
                except Exception:  # noqa: BLE001
                    pass
            for oid_bin, ent in list(self._incoming_objects.items()):
                if ent["last_used"] < cutoff:
                    self._incoming_objects.pop(oid_bin, None)
                    try:
                        ent["buf"].abort()
                    except Exception:  # noqa: BLE001
                        pass

    async def DeleteObject(self, object_id_bin: bytes) -> dict:
        from ray_tpu._private.ids import ObjectID

        if self.store is not None:
            try:
                self.store.delete(ObjectID(object_id_bin))
            except Exception:  # noqa: BLE001
                pass
        with self._spill_lock:
            ent = self.spilled.pop(bytes(object_id_bin), None)
        if ent is not None:
            try:
                os.unlink(ent[0])
            except OSError:
                pass
        return {"ok": True}

    # ------------------------------------------------------------------
    async def GetState(self) -> dict:
        with self._spill_lock:
            n_spilled = len(self.spilled)
        return {
            "node_id": self.node_id,
            "total": self.resources.total,
            "available": self.resources.available,
            "num_workers": len(self.workers),
            "num_idle": len(self.idle_workers),
            "num_leases": len(self.leases),
            "pending_leases": len(self.pending),
            "bundles": list(self.committed_bundles.keys()),
            "spilled_objects": n_spilled,
            "spilled_bytes_total": self._spilled_bytes_total,
            "restored_bytes_total": self._restored_bytes_total,
            "num_oom_kills": self.num_oom_kills,
            "draining": self.draining,
            "drain_reason": self.drain_reason,
        }

    async def Ping(self) -> str:
        return "pong"

    # ------------------------------------------------------------------
    async def _heartbeat_loop(self) -> None:
        period = config.raylet_heartbeat_period_ms / 1000.0
        while True:
            try:
                # pending lease shapes feed the autoscaler's bin-packing
                # (reference: GcsAutoscalerStateManager demand aggregation)
                shapes = [dict(p.request.get("resources") or {})
                          for p in self.pending[:100]]
                reply = await self.gcs.acall(
                    "Heartbeat",
                    node_id=self.node_id,
                    available_resources=self.resources.available,
                    pending_shapes=shapes,
                    num_leases=len(self.leases),
                    draining=self.draining,
                    drain_remaining_s=max(
                        0.0, self.drain_deadline - time.monotonic())
                    if self.draining else 0.0,
                    drain_reason=self.drain_reason,
                    timeout=10,
                )
                if reply.get("reregister"):
                    await self._register()
                view = reply.get("cluster")
                if view:
                    self.cluster_view = view
                if "autoscaling" in reply:
                    # absent on reregister replies — don't flip to False
                    self.autoscaling_enabled = bool(reply["autoscaling"])
                drain = reply.get("drain")
                if drain is not None and not self.draining:
                    # the GCS-side Drain RPC never reached us (lost, or
                    # we restarted): the heartbeat reply re-issues it
                    await self.Drain(reason=drain.get("reason", ""),
                                     deadline_s=drain.get("deadline_s"))
            except Exception as e:  # noqa: BLE001
                logger.warning("heartbeat failed: %s", e)
            await asyncio.sleep(period)

    async def _reap_loop(self) -> None:
        """Detect dead worker processes; free leases; tell GCS (for actor
        fail-over) — reference: raylet owns worker procs and reports deaths.

        The sweep is O(workers) of pidfd polls held on the loop; its
        period scales with the pool so a 2,000-worker node spends the
        same loop share on reaping as a 10-worker one (death-notice
        latency degrades gracefully instead of the event loop)."""
        while True:
            await asyncio.sleep(min(4.0, 0.5 + 0.002 * len(self.workers)))
            for w in list(self.workers.values()):
                if w.proc.poll() is not None and not w.dead:
                    logger.warning("worker %s exited with %s", w.worker_id[:8], w.proc.returncode)
                    lease = self.leases.get(w.busy_lease) if w.busy_lease else None
                    addr = w.addr
                    if lease is not None:
                        self._release_lease(lease, worker_dead=True)
                    else:
                        w.dead = True
                        self.workers.pop(w.worker_id, None)
                        try:
                            self.idle_workers.remove(w)
                        except ValueError:
                            pass
                    if addr is not None:
                        # queued, not fire-and-forget: a death during GCS
                        # downtime must still be delivered after the GCS
                        # restarts, or replayed ALIVE actors point at dead
                        # workers forever
                        self._pending_death_notices.append({
                            "node_id": self.node_id,
                            "worker_id": w.worker_id,
                            "worker_addr": addr,
                        })
            if self._pending_death_notices and not self._death_flush_running:
                # background task with a short timeout: a hung GCS must
                # not stall the reap loop's death detection
                asyncio.ensure_future(self._flush_death_notices())
            await self._replenish_workers()
            self._kick_drain()

    async def _replenish_workers(self) -> None:
        """Respawn idle workers toward the recent lease-demand peak after
        churn. Bounded by the creation-gate budget, and the peak decays
        30s after the last grant, so a finished burst's spares idle out
        through the normal reaper instead of flapping."""
        now = time.monotonic()
        if self.draining:
            return
        if now - self._recent_lease_ts > 30.0:
            self._recent_lease_peak = 0
            return
        target = (min(self._recent_lease_peak,
                      config.actor_creation_concurrency)
                  - len(self.leases))
        if target > 0:
            await self.PrestartWorkers(count=target)

    async def _flush_death_notices(self) -> None:
        self._death_flush_running = True
        try:
            while self._pending_death_notices:
                notice = self._pending_death_notices[0]
                try:
                    await self.gcs.acall(
                        "NotifyWorkerDeath", timeout=3, **notice)
                except Exception:  # noqa: BLE001
                    return  # GCS unreachable — retried next reap tick
                self._pending_death_notices.pop(0)
        finally:
            self._death_flush_running = False

    async def _log_tail_loop(self) -> None:
        """Tail this node's worker log files and push appended lines to the
        GCS log buffer (reference: _private/log_monitor.py), where the
        driver's log-to-driver thread picks them up."""
        offsets: Dict[str, int] = {}
        loop = asyncio.get_event_loop()
        while True:
            await asyncio.sleep(1.0)

            def _collect():
                batches = []
                try:
                    names = os.listdir(self.session_dir)
                except OSError:
                    return batches
                for fname in names:
                    if not (fname.startswith("worker-") and fname.endswith(".log")):
                        continue
                    path = os.path.join(self.session_dir, fname)
                    try:
                        size = os.path.getsize(path)
                        off = offsets.get(fname, 0)
                        if size <= off:
                            continue
                        with open(path, "rb") as f:
                            f.seek(off)
                            data = f.read(64 * 1024)
                        # only consume complete lines: a partial trailing
                        # line (mid-write, or chunk-cap split) stays for
                        # the next cycle — but a single line LONGER than
                        # the chunk must be consumed anyway or the tailer
                        # wedges on it forever
                        cut = data.rfind(b"\n")
                        if cut < 0:
                            if len(data) < 64 * 1024:
                                continue  # partial line, retry next cycle
                        else:
                            data = data[: cut + 1]
                        offsets[fname] = off + len(data)
                        lines = data.decode(errors="replace").splitlines()
                        if lines:
                            batches.append((fname[len("worker-"):-len(".log")], lines))
                    except OSError:
                        continue
                return batches

            batches = await loop.run_in_executor(None, _collect)
            for worker_id, lines in batches:
                try:
                    await self.gcs.acall(
                        "PublishLogs", node_id=self.node_id,
                        worker_id=worker_id, lines=lines, timeout=10,
                    )
                except Exception:  # noqa: BLE001
                    pass

    # -- OOM worker killing (reference: raylet memory monitor +
    # worker_killing_policy_group_by_owner.h: under host-memory
    # pressure, kill a worker from the owner-group with the MOST
    # workers — the fan-out most likely responsible — youngest first,
    # so the least progress is lost and its retriable task resubmits) --
    def _memory_pct(self) -> float:
        path = config.testing_memory_pct_file
        if path:
            try:
                with open(path) as f:
                    return float(f.read().strip())
            except (OSError, ValueError):
                return 0.0
        import psutil

        return float(psutil.virtual_memory().percent)

    def _pick_oom_victim(self) -> Optional["Lease"]:
        groups: Dict[Tuple, List[Lease]] = {}
        for lease in self.leases.values():
            if lease.worker.dead:
                continue
            # group by owner: the job, with each actor its own group
            # (reference groups by the task owner's id)
            key = (lease.job_id, lease.for_actor or "")
            groups.setdefault(key, []).append(lease)
        if not groups:
            return None
        biggest = max(groups.values(), key=len)
        return max(biggest, key=lambda le: le.granted_at)  # youngest

    async def _memory_monitor_loop(self) -> None:
        while True:
            await asyncio.sleep(config.memory_monitor_period_s)
            if config.memory_usage_threshold >= 1.0:
                continue  # disabled
            pct = self._memory_pct()
            if pct < config.memory_usage_threshold * 100.0:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            logger.warning(
                "memory pressure %.0f%% >= %.0f%%: killing worker %s "
                "(job %s, group-by-owner policy)", pct,
                config.memory_usage_threshold * 100.0,
                victim.worker.worker_id[:8], victim.job_id[:8])
            victim.worker.dead = True
            try:
                victim.worker.proc.kill()
            except Exception:  # noqa: BLE001
                pass
            self.num_oom_kills += 1
            # the reap loop + caller-side worker-failure handling do the
            # rest: lease released, task retried elsewhere

    async def _idle_reaper_loop(self) -> None:
        while True:
            await asyncio.sleep(5)
            cutoff = time.monotonic() - config.worker_idle_timeout_s
            keep: List[WorkerHandle] = []
            for w in self.idle_workers:
                if w.idle_since < cutoff and len(self.workers) > 1:
                    w.dead = True
                    self.workers.pop(w.worker_id, None)
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
                else:
                    keep.append(w)
            self.idle_workers = keep

    async def DebugDump(self, reason: str = "requested",
                        info: Optional[dict] = None) -> dict:
        """Flight-recorder shard on request (GCS fan-out / operators)."""
        path = obs_dump.dump_now(reason, extra=info)
        return {"ok": path is not None, "path": path}

    async def _register(self) -> None:
        await self.gcs.acall(
            "RegisterNode",
            node_id=self.node_id,
            address=(self.server.host, self.server.port),
            store_socket=self.store_socket,
            total_resources=self.resources.total,
            is_head=self.is_head,
            labels=self.labels,
            agent_port=getattr(self, "agent_port", 0),
            timeout=30,
        )

    def _loop_handle(self) -> LoopHandle:
        h = getattr(self, "_loop_handle_cached", None)
        if h is None:
            h = self._loop_handle_cached = LoopHandle(
                asyncio.get_event_loop())
        return h

    async def run(self) -> None:
        # start the native object store daemon for this node (no-evict:
        # the spill path below preserves data instead of LRU-dropping it)
        from ray_tpu._private.object_store.client import StoreClient, start_store_process

        os.makedirs(self.spill_dir, exist_ok=True)
        self.store_proc = start_store_process(
            self.store_socket, self.store_capacity, no_evict=True
        )
        self.store = StoreClient(self.store_socket)
        # gcs client rides this raylet's OWN event loop (LoopHandle): no
        # cross-thread handoff per heartbeat/lease-path RPC
        self.gcs = RpcClient(self.gcs_addr[0], self.gcs_addr[1],
                             self._loop_handle())
        # daemon-process observability wiring: no global_worker here, so
        # the event flusher and dump path get their identity/transport
        # explicitly
        obs_events.set_process_ident(f"raylet-{self.node_id[:8]}")
        obs_events.set_gcs_client(self.gcs)
        obs_dump.set_run_tag(f"{self.gcs_addr[0]}:{self.gcs_addr[1]}")
        obs_dump.install("raylet")

        server_task = asyncio.ensure_future(self.server.serve_forever())
        # wait until the port is bound
        while self.server.port == 0:
            await asyncio.sleep(0.01)
        # per-node observability agent, colocated on this event loop
        # (reference: dashboard/agent.py:35 — one agent per node)
        try:
            from ray_tpu.dashboard.agent import NodeAgent

            self.agent = NodeAgent(self, host=self.server.host)
            _, self.agent_port = await self.agent.start()
        except Exception:  # noqa: BLE001 — observability must not block boot
            logger.exception("node agent failed to start")
            self.agent_port = 0
        await self._register()
        asyncio.ensure_future(self._heartbeat_loop())
        asyncio.ensure_future(self._reap_loop())
        asyncio.ensure_future(self._idle_reaper_loop())
        asyncio.ensure_future(self._memory_monitor_loop())
        asyncio.ensure_future(self._drain_loop())
        asyncio.ensure_future(self._pull_pin_sweeper_loop())
        if config.log_to_driver:
            asyncio.ensure_future(self._log_tail_loop())
        if config.worker_pool_prestart_workers:
            for _ in range(int(self.resources.total.get("CPU", 1))):
                self._spawn_worker()
        try:
            await server_task
        finally:
            self.shutdown_procs()

    def shutdown_procs(self) -> None:
        for w in self.workers.values():
            try:
                w.proc.terminate()
            except Exception:
                pass
        if self._zygote is not None:
            self._zygote.stop()
        if self.store_proc is not None:
            try:
                self.store_proc.terminate()
            except Exception:
                pass


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--gcs-addr", required=True)  # host:port
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources-json", required=True)
    parser.add_argument("--store-socket", required=True)
    parser.add_argument("--store-capacity", type=int, required=True)
    parser.add_argument("--is-head", action="store_true")
    parser.add_argument("--session-dir", default="")
    parser.add_argument("--port-file", default="")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument("--labels-json", default="")
    args = parser.parse_args()
    logging.basicConfig(level=args.log_level, format="[raylet] %(levelname)s %(message)s")

    # -- diagnostics: record how this process exits ---------------------
    import faulthandler
    import signal as _signal

    faulthandler.enable()

    def _sig_logger(signum, frame):
        logger.info("raylet received signal %s; exiting", signum)
        try:
            raylet.shutdown_procs()
        except NameError:
            pass
        os._exit(128 + signum)

    _signal.signal(_signal.SIGTERM, _sig_logger)

    import json

    host, port_s = args.gcs_addr.rsplit(":", 1)
    raylet = Raylet(
        node_id=args.node_id,
        gcs_addr=(host, int(port_s)),
        resources=json.loads(args.resources_json),
        store_socket=args.store_socket,
        store_capacity=args.store_capacity,
        port=args.port,
        is_head=args.is_head,
        session_dir=args.session_dir,
        labels=json.loads(args.labels_json) if args.labels_json else None,
    )

    async def _run():
        task = asyncio.ensure_future(raylet.run())
        while raylet.server.port == 0:
            await asyncio.sleep(0.01)
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(raylet.server.port))
            os.replace(tmp, args.port_file)
        await task

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass
    finally:
        raylet.shutdown_procs()


if __name__ == "__main__":
    main()
