"""Distributed reference counting (per-worker part).

Reference: src/ray/core_worker/reference_counter.h:44. Each worker tracks,
per ObjectID: local refcount (live ObjectRef pythons), submitted-task count
(refs in flight as pending task args), and borrower state. The *owner* of an
object (the worker that created it) additionally tracks borrowers and frees
the object from the store when the global count reaches zero.

Round-1 scope: correct local counting + owner-side free callbacks +
borrower registration via RPC hooks the cluster runtime installs. Lineage
pinning hooks are present (``set_lineage_pinned``) for reconstruction.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set, Tuple

from ray_tpu._private import debug_locks

from ray_tpu._private.ids import ObjectID


@dataclass
class _Ref:
    local_refs: int = 0
    submitted_task_refs: int = 0
    # owner side: borrower address -> epoch of its latest AddBorrower.
    # A borrower sends RemoveBorrower (carrying the highest epoch it knows)
    # once its total interest — deserialized claims + unclaimed handoffs —
    # hits zero; the owner ignores a Remove older than the stored epoch, so
    # a stale Remove racing a concurrent re-borrow cannot wipe a live
    # registration (round-2 review finding).
    borrowers: Dict[Tuple[str, int], int] = field(default_factory=dict)
    borrow_epoch: int = 0
    owned: bool = False
    lineage_pinned: bool = False
    pending_creation: bool = False


class ReferenceCounter:
    def __init__(self) -> None:
        self._lock = debug_locks.maybe_wrap(
            threading.RLock(), "reference_counter.ReferenceCounter._lock")
        self._refs: Dict[ObjectID, _Ref] = {}
        # called when an *owned* object's global count hits zero
        self._on_zero: Optional[Callable[[ObjectID], None]] = None
        # called when a *borrowed* (non-owned) ref's local count hits zero
        # (the core worker deregisters with the owner)
        self._on_borrow_released: Optional[Callable[[ObjectID], None]] = None
        self._frozen = False

    def set_on_zero_callback(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_zero = cb

    def set_borrow_release_callback(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_borrow_released = cb

    def freeze(self) -> None:
        """Stop issuing on-zero callbacks (during shutdown)."""
        self._frozen = True

    # -- ownership --------------------------------------------------------
    def add_owned_object(self, oid: ObjectID, pending_creation: bool = False) -> None:
        with self._lock:
            r = self._refs.setdefault(oid, _Ref())
            r.owned = True
            r.pending_creation = pending_creation

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            r = self._refs.get(oid)
            return bool(r and r.owned)

    def set_lineage_pinned(self, oid: ObjectID, pinned: bool) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r:
                r.lineage_pinned = pinned

    # -- local counting ---------------------------------------------------
    def add_local_reference(self, oid: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).local_refs += 1

    def remove_local_reference(self, oid: ObjectID) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.local_refs -= 1
            action = self._maybe_release(oid, r)
        self._run_release_action(action, oid)

    def add_submitted_task_ref(self, oid: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).submitted_task_refs += 1

    def remove_submitted_task_ref(self, oid: ObjectID) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.submitted_task_refs -= 1
            action = self._maybe_release(oid, r)
        self._run_release_action(action, oid)

    # -- borrowers (owner side; reference: reference_counter.h:44 borrower
    # bookkeeping — a borrower process registers before it may read, the
    # owner keeps the object alive until every borrower deregisters) -----
    def add_borrower(self, oid: ObjectID, borrower_addr: Tuple[str, int]) -> Optional[int]:
        """Owner side. Returns the registration epoch, or None when the
        object's ref entry is already gone — i.e. the object was freed;
        recreating a zombie entry would make readers see 'pending' forever."""
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return None
            r.borrow_epoch += 1
            r.borrowers[borrower_addr] = r.borrow_epoch
            return r.borrow_epoch

    def borrower_addrs(self) -> Dict[Tuple[str, int], Set[ObjectID]]:
        """Owner side: every registered borrower address -> oids it pins.
        Used by the core worker's liveness sweep to drop borrowers whose
        process died without deregistering (reference: WaitForRefRemoved,
        reference_counter.h:44)."""
        out: Dict[Tuple[str, int], Set[ObjectID]] = {}
        with self._lock:
            for oid, r in self._refs.items():
                for addr in r.borrowers:
                    out.setdefault(addr, set()).add(oid)
        return out

    def remove_borrower(
        self,
        oid: ObjectID,
        borrower_addr: Tuple[str, int],
        epoch: Optional[int] = None,
    ) -> None:
        """Owner side. ``epoch=None`` removes unconditionally (borrower
        death); otherwise the removal only applies if no newer AddBorrower
        from that address has been recorded since."""
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            stored = r.borrowers.get(borrower_addr)
            if stored is None:
                return
            if epoch is not None and stored > epoch:
                return  # stale remove: a newer registration exists
            r.borrowers.pop(borrower_addr, None)
            action = self._maybe_release(oid, r)
        self._run_release_action(action, oid)

    # -- internal ---------------------------------------------------------
    def _maybe_release(self, oid: ObjectID, r: _Ref) -> Optional[Callable]:
        """Must be called with the lock held; returns the release callback
        to invoke AFTER dropping the lock (callbacks do store/network IO —
        running them under the lock would stall every ref-count op)."""
        if r.local_refs <= 0 and r.submitted_task_refs <= 0 and not r.borrowers:
            owned = r.owned
            pinned = r.lineage_pinned
            del self._refs[oid]
            if self._frozen:
                return None
            if owned and not pinned:
                return self._on_zero
            if not owned:
                return self._on_borrow_released
        return None

    @staticmethod
    def _run_release_action(action: Optional[Callable], oid: ObjectID) -> None:
        if action is not None:
            try:
                action(oid)
            except Exception:
                pass

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def has_reference(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._refs
