"""Distributed reference counting (per-worker part).

Reference: src/ray/core_worker/reference_counter.h:44. Each worker tracks,
per ObjectID: local refcount (live ObjectRef pythons), submitted-task count
(refs in flight as pending task args), and borrower state. The *owner* of an
object (the worker that created it) additionally tracks borrowers and frees
the object from the store when the global count reaches zero.

Round-1 scope: correct local counting + owner-side free callbacks +
borrower registration via RPC hooks the cluster runtime installs. Lineage
pinning hooks are present (``set_lineage_pinned``) for reconstruction.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set, Tuple

from ray_tpu._private.ids import ObjectID


@dataclass
class _Ref:
    local_refs: int = 0
    submitted_task_refs: int = 0
    borrowers: Set[Tuple[str, int]] = field(default_factory=set)
    owned: bool = False
    lineage_pinned: bool = False
    pending_creation: bool = False


class ReferenceCounter:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._refs: Dict[ObjectID, _Ref] = {}
        # called when an *owned* object's global count hits zero
        self._on_zero: Optional[Callable[[ObjectID], None]] = None
        self._frozen = False

    def set_on_zero_callback(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_zero = cb

    def freeze(self) -> None:
        """Stop issuing on-zero callbacks (during shutdown)."""
        self._frozen = True

    # -- ownership --------------------------------------------------------
    def add_owned_object(self, oid: ObjectID, pending_creation: bool = False) -> None:
        with self._lock:
            r = self._refs.setdefault(oid, _Ref())
            r.owned = True
            r.pending_creation = pending_creation

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            r = self._refs.get(oid)
            return bool(r and r.owned)

    def set_lineage_pinned(self, oid: ObjectID, pinned: bool) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r:
                r.lineage_pinned = pinned

    # -- local counting ---------------------------------------------------
    def add_local_reference(self, oid: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).local_refs += 1

    def remove_local_reference(self, oid: ObjectID) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.local_refs -= 1
            self._maybe_release(oid, r)

    def add_submitted_task_ref(self, oid: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).submitted_task_refs += 1

    def remove_submitted_task_ref(self, oid: ObjectID) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.submitted_task_refs -= 1
            self._maybe_release(oid, r)

    # -- borrowers (installed by cluster runtime) -------------------------
    def add_borrower(self, oid: ObjectID, borrower_addr: Tuple[str, int]) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).borrowers.add(borrower_addr)

    def remove_borrower(self, oid: ObjectID, borrower_addr: Tuple[str, int]) -> None:
        with self._lock:
            r = self._refs.get(oid)
            if r is None:
                return
            r.borrowers.discard(borrower_addr)
            self._maybe_release(oid, r)

    # -- internal ---------------------------------------------------------
    def _maybe_release(self, oid: ObjectID, r: _Ref) -> None:
        if r.local_refs <= 0 and r.submitted_task_refs <= 0 and not r.borrowers:
            owned = r.owned
            pinned = r.lineage_pinned
            del self._refs[oid]
            if owned and not pinned and self._on_zero and not self._frozen:
                try:
                    self._on_zero(oid)
                except Exception:
                    pass

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def has_reference(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._refs
