"""Asyncio RPC layer: length-prefixed pickle frames over TCP.

Reference analogue: src/ray/rpc/ (GrpcServer grpc_server.h:93,
ClientCallManager client_call.h:61, RetryableGrpcClient) — rebuilt on
asyncio instead of gRPC/protobuf for the Python control plane; the wire
format is a 4-byte length + 1-byte flags + pickle body. Includes the
reference's RPC fault-injection hook (rpc_chaos.h:8) driven by the
``testing_rpc_failure`` config flag ("method=prob" comma list).

Frame layout:
    request:  [u64 call_id][u8 kind][pickle (method, kwargs)]
    response: [u64 call_id][u8 kind][pickle (ok, payload)]
kind: 0 = request, 1 = response, 2 = oneway (no response expected).
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import random
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu._private.config import config

logger = logging.getLogger(__name__)

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ONEWAY = 2


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError, ConnectionError):
    pass


class RemoteError(RpcError):
    """Handler raised; carries the remote traceback string."""

    def __init__(self, message: str):
        super().__init__(message)


def _chaos_action(method: str) -> Optional[str]:
    """Parse ``testing_rpc_failure`` and roll the dice for one call.

    Spec: comma list of ``Method=prob[:kind]`` where kind is
    ``request`` (drop before the handler runs — the default),
    ``response`` (handler runs, reply is dropped — side effects happen,
    the caller sees a timeout), or ``delay:<ms>`` (in-flight latency).
    Mirrors the reference's Request/Response/InFlight failure kinds
    (src/ray/rpc/rpc_chaos.h:8).
    """
    spec = config.testing_rpc_failure
    if not spec:
        return None
    for part in spec.split(","):
        if "=" not in part:
            continue
        name, rest = part.split("=", 1)
        if name != method and name != "*":
            continue
        bits = rest.split(":", 1)
        try:
            prob = float(bits[0])
        except ValueError:
            return None
        if random.random() < prob:
            return bits[1] if len(bits) > 1 else "request"
        return None
    return None


async def _read_frame(reader: asyncio.StreamReader) -> Tuple[int, int, bytes]:
    header = await reader.readexactly(13)
    (length,) = struct.unpack_from("<I", header, 0)
    (call_id,) = struct.unpack_from("<Q", header, 4)
    kind = header[12]
    body = await reader.readexactly(length)
    return call_id, kind, body


def _write_frame(writer: asyncio.StreamWriter, call_id: int, kind: int, body: bytes) -> None:
    writer.write(struct.pack("<IQB", len(body), call_id, kind) + body)


class EventLoopThread:
    """A dedicated asyncio loop running on a daemon thread.

    Reference analogue: instrumented_io_context — each component runs its
    handlers on one loop; we record per-handler latency the same way.
    """

    _singleton: Optional["EventLoopThread"] = None
    _singleton_lock = threading.Lock()

    def __init__(self, name: str = "rpc-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    @classmethod
    def get_global(cls) -> "EventLoopThread":
        with cls._singleton_lock:
            if cls._singleton is None or not cls._singleton._thread.is_alive():
                cls._singleton = cls("rpc-io-global")
            return cls._singleton

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        # big default executor: sync handlers (task execution, owner object
        # serving) block threads, and nested tasks must not starve the pool
        from concurrent.futures import ThreadPoolExecutor

        self.loop.set_default_executor(ThreadPoolExecutor(max_workers=128, thread_name_prefix="rpc-exec"))
        self._started.set()
        self.loop.run_forever()

    def run_coro(self, coro: Awaitable, timeout: Optional[float] = None) -> Any:
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, cb: Callable, *args) -> None:
        self.loop.call_soon_threadsafe(cb, *args)

    def stop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)


class RpcServer:
    """Serve registered handlers. Handlers may be sync or async; they run on
    the server's event loop (async) or a thread pool (sync)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, name: str = "rpc"):
        self.host = host
        self.port = port
        self.name = name
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop_thread: Optional[EventLoopThread] = None
        self._handler_stats: Dict[str, Tuple[int, float]] = {}
        # awaited after each handler, before its response frame is sent.
        # The GCS hangs its WAL group-commit barrier here: handlers
        # append durable records without fsync, and one fsync covers
        # every record appended by the batch of handlers that completed
        # this tick — durability-before-ack without a disk sync per
        # mutation.
        self.pre_response: Optional[Callable[[], Awaitable[None]]] = None

    def register(self, method: str, handler: Callable) -> None:
        self._handlers[method] = handler

    def register_instance(self, obj: Any, prefix: str = "") -> None:
        """Register every public method of ``obj`` as a handler."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self._handlers[prefix + name] = fn

    # -- lifecycle --------------------------------------------------------
    def start(self, loop_thread: Optional[EventLoopThread] = None) -> Tuple[str, int]:
        self._loop_thread = loop_thread or EventLoopThread(name=f"{self.name}-io")
        self._loop_thread.run_coro(self._start_async())
        return self.host, self.port

    async def _start_async(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        """For processes whose main thread is the event loop."""
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        async with self._server:
            await self._server.serve_forever()

    def stop(self) -> None:
        if self._loop_thread and self._server:
            async def _close():
                self._server.close()

            try:
                self._loop_thread.run_coro(_close(), timeout=5)
            except Exception:
                pass

    # -- serving ----------------------------------------------------------
    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                call_id, kind, body = await _read_frame(reader)
                asyncio.ensure_future(self._dispatch(call_id, kind, body, writer))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            logger.exception("%s: connection handler error", self.name)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, call_id: int, kind: int, body: bytes, writer: asyncio.StreamWriter) -> None:
        t0 = time.monotonic()
        method = "?"
        try:
            method, kwargs = pickle.loads(body)
            chaos = _chaos_action(method)
            if chaos == "request":
                logger.warning("chaos: dropping rpc %s", method)
                return  # simulate lost request
            if chaos and chaos.startswith("delay"):
                ms = float(chaos.split(":", 1)[1]) if ":" in chaos else 100.0
                logger.warning("chaos: delaying rpc %s by %sms", method, ms)
                await asyncio.sleep(ms / 1000.0)
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"{self.name}: no handler for {method!r}")
            if asyncio.iscoroutinefunction(handler):
                result = await handler(**kwargs)
            else:
                result = await asyncio.get_event_loop().run_in_executor(
                    None, lambda: handler(**kwargs)
                )
            if chaos == "response":
                # handler side effects happened; the reply is lost
                logger.warning("chaos: dropping reply of rpc %s", method)
                return
            if kind == KIND_ONEWAY:
                return
            payload = pickle.dumps((True, result), protocol=5)
        except Exception as e:  # noqa: BLE001
            if kind == KIND_ONEWAY:
                logger.exception("%s: oneway handler %s failed", self.name, method)
                return
            import traceback

            payload = pickle.dumps((False, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"), protocol=5)
        dt = time.monotonic() - t0
        if dt * 1000 > config.event_loop_slow_handler_ms:
            logger.warning("%s: slow handler %s took %.1fms", self.name, method, dt * 1000)
        if self.pre_response is not None:
            try:
                await self.pre_response()
            except Exception:  # noqa: BLE001
                logger.exception("%s: pre_response hook failed", self.name)
        try:
            _write_frame(writer, call_id, KIND_RESPONSE, payload)
            await writer.drain()
        except (ConnectionError, RuntimeError):
            pass


_oneway_tasks: set = set()


def _oneway_done(task) -> None:
    _oneway_tasks.discard(task)
    exc = task.exception() if not task.cancelled() else None
    if exc is not None:
        logger.debug("oneway rpc failed: %s", exc)


class RpcClient:
    """Persistent connection with pipelined calls + reconnect/retry."""

    def __init__(self, host: str, port: int, loop_thread: Optional[EventLoopThread] = None):
        self.host = host
        self.port = port
        self._loop_thread = loop_thread or EventLoopThread.get_global()
        self._lock = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._conn_lock: Optional[asyncio.Lock] = None

    # -- async internals --------------------------------------------------
    async def _ensure_connected(self) -> None:
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                timeout=config.rpc_connect_timeout_s,
            )
            self._writer = writer
            self._reader_task = asyncio.ensure_future(self._read_loop(reader))

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                call_id, kind, body = await _read_frame(reader)
                fut = self._pending.pop(call_id, None)
                if fut is not None and not fut.done():
                    fut.set_result(body)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._writer = None
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(RpcConnectionError(f"connection to {self.host}:{self.port} lost"))
            self._pending.clear()

    async def _call_async(self, method: str, kwargs: dict, oneway: bool, timeout: Optional[float]) -> Any:
        """Must run on self._loop_thread.loop — all connection state
        (writer, pending futures, read loop) is affine to that loop."""
        if timeout is not None and timeout < 0:
            timeout = None  # negative = wait forever (long-running tasks)
        await self._ensure_connected()
        with self._lock:
            self._next_id += 1
            call_id = self._next_id
        body = pickle.dumps((method, kwargs), protocol=5)
        if oneway:
            _write_frame(self._writer, call_id, KIND_ONEWAY, body)
            await self._writer.drain()
            return None
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[call_id] = fut
        _write_frame(self._writer, call_id, KIND_REQUEST, body)
        await self._writer.drain()
        body = await asyncio.wait_for(fut, timeout=timeout)
        ok, payload = pickle.loads(body)
        if not ok:
            raise RemoteError(payload)
        return payload

    # -- public sync API --------------------------------------------------
    def call(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        timeout = timeout if timeout is not None else config.rpc_call_timeout_s
        outer = None if timeout < 0 else timeout + 5
        return self._loop_thread.run_coro(
            self._call_async(method, kwargs, oneway=False, timeout=timeout),
            timeout=outer,
        )

    def call_retrying(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        """Retry on connection errors with exponential backoff (reference:
        retryable_grpc_client.h)."""
        delay = config.rpc_retry_base_delay_ms / 1000.0
        last: Optional[Exception] = None
        for _ in range(max(1, config.rpc_max_retries)):
            try:
                return self.call(method, timeout=timeout, **kwargs)
            except (RpcConnectionError, ConnectionError, asyncio.TimeoutError, TimeoutError, OSError) as e:
                last = e
                time.sleep(delay)
                delay = min(delay * 2, config.rpc_retry_max_delay_ms / 1000.0)
        raise RpcConnectionError(f"rpc {method} to {self.host}:{self.port} failed after retries: {last}")

    def call_oneway(self, method: str, **kwargs) -> None:
        coro = self._call_async(method, kwargs, oneway=True, timeout=None)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop_thread.loop:
            # caller IS the io loop (e.g. a refcount release triggered
            # from a dispatcher coroutine): blocking run_coro here would
            # deadlock the loop on itself — fire and forget instead.
            # Pin the task (asyncio holds only weak refs) so GC cannot
            # collect it mid-flight, and drain its exception.
            task = asyncio.ensure_future(coro)
            _oneway_tasks.add(task)
            task.add_done_callback(_oneway_done)
            return
        self._loop_thread.run_coro(coro, timeout=30)

    async def acall(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        """Async call, safe from ANY event loop: the I/O always executes on
        this client's owning loop (cross-loop use of one cached client was a
        silent-hang bug — futures created on loop A resolved from loop B
        never wake A)."""
        timeout = timeout if timeout is not None else config.rpc_call_timeout_s
        running = asyncio.get_event_loop()
        if running is self._loop_thread.loop:
            return await self._call_async(method, kwargs, oneway=False, timeout=timeout)
        cf = asyncio.run_coroutine_threadsafe(
            self._call_async(method, kwargs, oneway=False, timeout=timeout),
            self._loop_thread.loop,
        )
        return await asyncio.wrap_future(cf)

    def close(self) -> None:
        w = self._writer

        async def _close():
            if w is not None:
                try:
                    w.close()
                except Exception:
                    pass

        try:
            self._loop_thread.run_coro(_close(), timeout=5)
        except Exception:
            pass


_client_cache: Dict[Tuple[str, int], RpcClient] = {}
_client_cache_lock = threading.Lock()


def get_client(addr: Tuple[str, int]) -> RpcClient:
    """Process-wide client cache — one connection per peer."""
    with _client_cache_lock:
        c = _client_cache.get(addr)
        if c is None:
            c = RpcClient(addr[0], addr[1])
            _client_cache[addr] = c
        return c


def clear_client_cache() -> None:
    with _client_cache_lock:
        for c in _client_cache.values():
            c.close()
        _client_cache.clear()
