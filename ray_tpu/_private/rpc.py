"""Asyncio RPC layer: length-prefixed pickle frames over TCP.

Reference analogue: src/ray/rpc/ (GrpcServer grpc_server.h:93,
ClientCallManager client_call.h:61, RetryableGrpcClient) — rebuilt on
asyncio instead of gRPC/protobuf for the Python control plane; the wire
format is a 4-byte length + 1-byte flags + pickle body. Includes the
reference's RPC fault-injection hook (rpc_chaos.h:8) driven by the
``testing_rpc_failure`` config flag ("method=prob" comma list).

Frame layout:
    request:  [u64 call_id][u8 kind][pickle (method, kwargs)]
    response: [u64 call_id][u8 kind][pickle (ok, payload)]
kind: 0 = request, 1 = response, 2 = oneway (no response expected).
The high bit of ``kind`` (0x80) flags an out-of-band framed body:
    [u32 meta_len][meta pickle][u32 nbuffers][u64 len, raw bytes]...
where the payload buffers were captured by the pickle-5 buffer callback
and travel as zero-copy views — large numpy/bytes payloads are never
joined into one bytes object on the send side. Receive-side contract:
out-of-band payloads (buffers >= 4 KiB) reconstruct as READ-ONLY arrays
viewing the frame buffer (np.copy() to mutate); sub-4KiB buffers stay
in-band and arrive writable as before.

Framing fast path: header + body + out-of-band buffers reach the socket
through gather writes (no concatenation of large segments), and small
frames queued within one event-loop tick coalesce into a single
transport write.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import random
import struct
import sys
import threading
import time
from collections import deque
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu._private import debug_locks, fastpath
from ray_tpu._private.config import config

logger = logging.getLogger(__name__)

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ONEWAY = 2
# flag bit on ``kind``: body uses the meta + out-of-band buffer framing
KIND_OOB_FLAG = 0x80
KIND_MASK = 0x7F

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Event-loop lag flight recorder: dispatches that measurably held the
# loop (the same loop_held the slow-handler warning uses) land in a
# bounded ring, so a failure dump can show WHEN the control plane's
# loop was stalled and by which method — not just that a warning once
# scrolled by. ~1 ms floor keeps the ring to genuinely interesting
# samples; a deque append is cheap enough for the dispatch path.
_LOOP_LAG_MIN_S = 0.001
_loop_lag: deque = deque(maxlen=2048)


def _note_loop_held(server: str, method: str, held_s: float,
                    wall_s: float) -> None:
    _loop_lag.append((time.time(), server, method,
                      round(held_s * 1000.0, 3),
                      round(wall_s * 1000.0, 3)))


def loop_lag_samples() -> list:
    """Recent loop-held samples: [{ts, server, method, held_ms, wall_ms}]."""
    return [{"ts": t, "server": s, "method": m, "held_ms": h,
             "wall_ms": w} for (t, s, m, h, w) in list(_loop_lag)]

# Frames at or below this size coalesce: queued per-writer and flushed in
# one transport write at the end of the current event-loop tick, so a
# burst of small frames (actor-task batches, acks) costs one syscall.
_SMALL_FRAME_MAX = 8192
# Segments at least this large are handed to the transport as views (no
# concatenation); smaller neighbours are joined to bound syscall count.
_GATHER_CUTOFF = 32 * 1024
# Bodies above this size are pickled/unpickled on the executor, not the
# event loop, so one fat CreateActor/PushTask payload cannot stall every
# connection sharing the loop.
_LOOP_DECODE_MAX = 256 * 1024


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError, ConnectionError):
    pass


class RemoteError(RpcError):
    """Handler raised; carries the remote traceback string."""

    def __init__(self, message: str):
        super().__init__(message)


def _chaos_action(method: str) -> Optional[str]:
    """Parse ``testing_rpc_failure`` and roll the dice for one call.

    Spec: comma list of ``Method=prob[:kind]`` where kind is
    ``request`` (drop before the handler runs — the default),
    ``response`` (handler runs, reply is dropped — side effects happen,
    the caller sees a timeout), ``delay:<ms>`` (in-flight latency), or a
    bare number — ``Method=prob:delay_ms`` — which is shorthand for the
    delay kind (latency injection, not a failure). Mirrors the
    reference's Request/Response/InFlight failure kinds
    (src/ray/rpc/rpc_chaos.h:8).
    """
    spec = config.testing_rpc_failure
    if not spec:
        return None
    for part in spec.split(","):
        if "=" not in part:
            continue
        name, rest = part.split("=", 1)
        if name != method and name != "*":
            continue
        bits = rest.split(":", 1)
        try:
            prob = float(bits[0])
        except ValueError:
            return None
        if random.random() >= prob:
            return None
        if len(bits) == 1:
            return "request"
        kind = bits[1]
        try:
            return f"delay:{float(kind):g}"  # Method=prob:delay_ms form
        except ValueError:
            return kind
    return None


async def _read_frame(reader: asyncio.StreamReader) -> Tuple[int, int, bytes]:
    header = await reader.readexactly(13)
    length, call_id, kind = fastpath.unpack_header(header)
    body = await reader.readexactly(length)
    return call_id, kind, body


# buffers below this stay IN-band: they arrive writable (old semantics)
# and a tiny out-of-band segment saves nothing
_OOB_MIN_BYTES = 4096


def _encode_body(obj: Any) -> Tuple[int, list, int]:
    """Pickle an RPC body. LARGE buffer-protocol payloads (numpy arrays,
    ...) are captured by the pickle-5 buffer callback and stay OUT OF
    BAND as zero-copy view segments; sub-4KiB buffers serialize in-band
    (writable on receipt, as before). Returns (kind_flags, segments,
    total_len).

    NOTE the wire layout below deliberately mirrors
    serialization.SerializedValue.segments() / deserialize() — if one
    grows a header field or alignment padding, change both."""
    bufs: list = []

    def _cb(b: pickle.PickleBuffer):
        if b.raw().nbytes < _OOB_MIN_BYTES:
            return True  # truthy = pickle keeps the buffer in-band
        bufs.append(b)
        return False

    meta = pickle.dumps(obj, protocol=5, buffer_callback=_cb)
    if not bufs:
        return 0, [meta], len(meta)
    raws: list = []
    total = 8 + len(meta)
    for b in bufs:
        raw = b.raw()
        if raw.ndim != 1 or raw.format != "B":
            raw = raw.cast("B")
        raws.append(raw)
        total += 8 + raw.nbytes
    if total <= _GATHER_CUTOFF:
        # small OOB body: the coalescing sink would copy each borrowed
        # segment to owned bytes anyway — one codec pass builds the
        # whole owned body instead (fastpath.encode_body, native when
        # the extension is loaded)
        return KIND_OOB_FLAG, [fastpath.encode_body(meta, raws)], total
    segs: list = [_U32.pack(len(meta)), meta, _U32.pack(len(raws))]
    for raw in raws:
        segs.append(_U64.pack(raw.nbytes))
        segs.append(raw)
    return KIND_OOB_FLAG, segs, total


def _decode_body(kind: int, body: bytes) -> Any:
    """Inverse of _encode_body; out-of-band buffers are zero-copy views
    into the received body (fastpath codec: one native parse pass)."""
    if not kind & KIND_OOB_FLAG:
        return pickle.loads(body)
    meta, buffers = fastpath.decode_body(body)
    return pickle.loads(meta, buffers=buffers)


class _FrameSink:
    """Per-connection gather-write sink.

    The FIRST frame of an event-loop tick writes through immediately (a
    lone latency-sensitive call pays zero batching delay); small frames
    that follow in the SAME tick coalesce and go out as one transport
    write at tick end — a burst of N small frames (actor-task batches,
    acks) costs 2 syscalls instead of N. Large frames always write
    through, vectored: the header and sub-cutoff segments join into one
    small write, every large segment is handed to the transport as a
    view, uncopied.

    Borrow safety: on CPython <3.12 ``transport.write`` consumes data
    synchronously (sent, or copied into the transport's bytearray), so
    borrowed views are safe to mutate once write_frame returns. 3.12+
    selector transports may retain the view object in their write deque
    under backpressure, so there large segments are materialized before
    handoff — costs the one copy the old concatenating path always paid,
    only under backpressure-capable interpreters."""

    _WRITE_CONSUMES_VIEWS = sys.version_info < (3, 12)

    __slots__ = ("writer", "_small", "_tick_armed")

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self._small: list = []
        self._tick_armed = False

    def write_frame(self, call_id: int, kind: int, segs: list, total: int) -> None:
        first = not self._tick_armed
        if first:
            self._tick_armed = True
            asyncio.get_event_loop().call_soon(self._end_tick)
        if total <= _SMALL_FRAME_MAX and len(segs) == 1:
            # single-segment small frame (plain pickle body, or an OOB
            # body already joined by the codec): header + body assemble
            # in ONE fastpath allocation — owned bytes, so it is safe
            # both to coalesce and to hand to the transport directly
            frame = fastpath.build_frame(call_id, kind, segs[0])
            if first:
                self._flush_small()
                self.writer.write(frame)
            else:
                self._small.append(frame)
            return
        header = fastpath.pack_header(total, call_id, kind)
        if total <= _SMALL_FRAME_MAX and not first:
            # follower in this tick: coalesce. Segments must be owned
            # bytes, not borrowed views (caller may mutate after return).
            self._small.append(header)
            for s in segs:
                self._small.append(s if isinstance(s, bytes) else bytes(s))
            return
        self._flush_small()  # ordering: queued frames go out first
        acc: list = [header]
        for s in segs:
            n = len(s) if isinstance(s, bytes) else s.nbytes
            if n >= _GATHER_CUTOFF:
                if acc:
                    self.writer.write(b"".join(acc))
                    acc = []
                if not isinstance(s, bytes) and not self._WRITE_CONSUMES_VIEWS:
                    s = bytes(s)  # 3.12+: transport may retain the view
                self.writer.write(s)
            else:
                acc.append(s if isinstance(s, bytes) else bytes(s))
        if acc:
            self.writer.write(b"".join(acc))

    def _end_tick(self) -> None:
        self._tick_armed = False
        self._flush_small()

    def _flush_small(self) -> None:
        if not self._small:
            return
        data = b"".join(self._small)
        self._small.clear()
        try:
            self.writer.write(data)
        except Exception:  # noqa: BLE001 — connection already torn down
            pass


def _sink(writer: asyncio.StreamWriter) -> _FrameSink:
    s = getattr(writer, "_rt_sink", None)
    if s is None:
        s = writer._rt_sink = _FrameSink(writer)
    return s


def _send_frame(writer: asyncio.StreamWriter, call_id: int, kind: int, obj: Any) -> None:
    flags, segs, total = _encode_body(obj)
    _sink(writer).write_frame(call_id, kind | flags, segs, total)


class EventLoopThread:
    """A dedicated asyncio loop running on a daemon thread.

    Reference analogue: instrumented_io_context — each component runs its
    handlers on one loop; we record per-handler latency the same way.
    """

    _singleton: Optional["EventLoopThread"] = None
    _singleton_lock = threading.Lock()

    def __init__(self, name: str = "rpc-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    @classmethod
    def get_global(cls) -> "EventLoopThread":
        with cls._singleton_lock:
            if cls._singleton is None or not cls._singleton._thread.is_alive():
                cls._singleton = cls("rpc-io-global")
            return cls._singleton

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        # big default executor: sync handlers (task execution, owner object
        # serving) block threads, and nested tasks must not starve the pool
        from concurrent.futures import ThreadPoolExecutor

        self.loop.set_default_executor(ThreadPoolExecutor(max_workers=128, thread_name_prefix="rpc-exec"))
        self._started.set()
        self.loop.run_forever()

    def run_coro(self, coro: Awaitable, timeout: Optional[float] = None) -> Any:
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, cb: Callable, *args) -> None:
        self.loop.call_soon_threadsafe(cb, *args)

    def stop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        # reap the loop thread (bounded: run_forever returns right after
        # the stop above is processed); self-stop from a loop callback
        # must not join itself
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5)


class LoopHandle:
    """EventLoopThread-shaped handle for a loop the CALLING process
    already runs on its main thread (the gcs/raylet asyncio daemons).

    An RpcClient bound to this handle does its connection I/O on the
    daemon's own loop, so ``acall`` from a handler coroutine runs
    in-line — the default global EventLoopThread would put every
    outbound control RPC through two cross-thread handoffs (submit +
    wakeup), which on a 1-core host is a large slice of lease-grant and
    actor-creation latency."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop

    def run_coro(self, coro: Awaitable, timeout: Optional[float] = None) -> Any:
        """Foreign-thread entry (sync .call paths); never call from the
        owning loop itself — that would deadlock the loop on its result."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, cb: Callable, *args) -> None:
        self.loop.call_soon_threadsafe(cb, *args)


class RpcServer:
    """Serve registered handlers. Handlers may be sync or async; they run on
    the server's event loop (async) or a thread pool (sync)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, name: str = "rpc"):
        self.host = host
        self.port = port
        self.name = name
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop_thread: Optional[EventLoopThread] = None
        self._handler_stats: Dict[str, Tuple[int, float]] = {}
        # awaited after each handler, before its response frame is sent.
        # The GCS hangs its WAL group-commit barrier here: handlers
        # append durable records without fsync, and one fsync covers
        # every record appended by the batch of handlers that completed
        # this tick — durability-before-ack without a disk sync per
        # mutation.
        self.pre_response: Optional[Callable[[], Awaitable[None]]] = None
        # methods that legitimately park for their whole timeout (pubsub
        # long-polls): exempt from the slow-async-handler warning
        self._long_poll: set = set()
        # sync handlers cheap enough to run ON the loop (queue append,
        # memory-store put, dict bookkeeping): skipping the executor
        # handoff saves two thread hops per call — on a 1-core host that
        # is a large slice of small-RPC latency. Inline time counts as
        # loop-held, so the slow-handler warning polices the choice.
        self._inline: set = set()

    def register(self, method: str, handler: Callable,
                 long_poll: bool = False, inline: bool = False) -> None:
        self._handlers[method] = handler
        if long_poll:
            self._long_poll.add(method)
        if inline:
            self._inline.add(method)

    def register_instance(self, obj: Any, prefix: str = "") -> None:
        """Register every public method of ``obj`` as a handler."""
        for name in dir(obj):
            if name.startswith("_"):
                continue
            fn = getattr(obj, name)
            if callable(fn):
                self._handlers[prefix + name] = fn

    # -- lifecycle --------------------------------------------------------
    def start(self, loop_thread: Optional[EventLoopThread] = None) -> Tuple[str, int]:
        self._loop_thread = loop_thread or EventLoopThread(name=f"{self.name}-io")
        self._loop_thread.run_coro(self._start_async())
        return self.host, self.port

    async def _start_async(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        """For processes whose main thread is the event loop."""
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        async with self._server:
            await self._server.serve_forever()

    def stop(self) -> None:
        if self._loop_thread and self._server:
            async def _close():
                self._server.close()

            try:
                self._loop_thread.run_coro(_close(), timeout=5)
            except Exception:
                # the owning loop may already be gone at teardown; the
                # socket dies with the process either way
                logger.debug("%s: server close failed", self.name,
                             exc_info=True)

    # -- serving ----------------------------------------------------------
    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                call_id, kind, body = await _read_frame(reader)
                asyncio.ensure_future(self._dispatch(call_id, kind, body, writer))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            logger.exception("%s: connection handler error", self.name)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, call_id: int, kind: int, body: bytes, writer: asyncio.StreamWriter) -> None:
        t0 = time.monotonic()
        # Track time the loop is actually HELD by this dispatch (decode +
        # on-loop handler segments). Executor time is wall-clock for the
        # caller but does not stall sibling connections — the old warning
        # charged the whole handler to the loop and cried wolf on every
        # fat CreateActor that was already safely off-loop.
        loop_held = 0.0
        base_kind = kind & KIND_MASK
        method = "?"
        is_async = False
        loop = asyncio.get_event_loop()
        try:
            if len(body) > _LOOP_DECODE_MAX:
                # decode runs on the executor: wall time, not loop time
                method, kwargs = await loop.run_in_executor(
                    None, _decode_body, kind, body)
            else:
                method, kwargs = _decode_body(kind, body)
                loop_held += time.monotonic() - t0
            chaos = _chaos_action(method)
            if chaos == "request":
                logger.warning("chaos: dropping rpc %s", method)
                return  # simulate lost request
            if chaos and chaos.startswith("delay"):
                ms = float(chaos.split(":", 1)[1]) if ":" in chaos else 100.0
                logger.warning("chaos: delaying rpc %s by %sms", method, ms)
                await asyncio.sleep(ms / 1000.0)
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"{self.name}: no handler for {method!r}")
            is_async = asyncio.iscoroutinefunction(handler)
            if is_async:
                result = await handler(**kwargs)
            elif method in self._inline:
                # registered inline: cheap bookkeeping handler runs on
                # the loop directly; its time is loop-held by definition
                ti = time.monotonic()
                result = handler(**kwargs)
                loop_held += time.monotonic() - ti
            else:
                # sync handlers never run on the loop: the blocking part
                # of actor bootstrap (ctor-arg unpickling, zygote
                # handshake) executes on the thread pool
                result = await loop.run_in_executor(
                    None, lambda: handler(**kwargs)
                )
            if chaos == "response":
                # handler side effects happened; the reply is lost
                logger.warning("chaos: dropping reply of rpc %s", method)
                return
            if base_kind == KIND_ONEWAY:
                return
            te = time.monotonic()
            flags, segs, total = _encode_body((True, result))
            loop_held += time.monotonic() - te
        except Exception as e:  # noqa: BLE001
            if base_kind == KIND_ONEWAY:
                logger.exception("%s: oneway handler %s failed", self.name, method)
                return
            import traceback

            flags, segs, total = _encode_body(
                (False, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))
        dt = time.monotonic() - t0
        if loop_held >= _LOOP_LAG_MIN_S:
            _note_loop_held(self.name, method, loop_held, dt)
        # an inline handler's wall time inflates under process-wide GIL
        # saturation (every thread is equally stalled) — warn only well
        # past the threshold so a busy-but-healthy worker doesn't spam
        # slow-handler lines for queue appends
        held_budget_ms = config.event_loop_slow_handler_ms * (
            5 if method in self._inline else 1)
        if loop_held * 1000 > held_budget_ms:
            # decode/encode/framing time — genuinely holds the loop for
            # sync AND async handlers alike
            logger.warning(
                "%s: slow handler %s held the event loop %.1fms "
                "(%.1fms wall)", self.name, method, loop_held * 1000,
                dt * 1000)
        elif is_async and dt * 1000 > config.event_loop_slow_handler_ms \
                and method not in self._long_poll:
            # an async handler's awaits yield the loop, but CPU-bound
            # segments inside it do not — keep the wall-clock warning
            # for async handlers (registered long-polls excepted); sync
            # handlers run on the executor and no longer cry wolf here
            logger.warning("%s: slow handler %s took %.1fms",
                           self.name, method, dt * 1000)
        if self.pre_response is not None:
            try:
                await self.pre_response()
            except Exception:  # noqa: BLE001
                logger.exception("%s: pre_response hook failed", self.name)
        try:
            _sink(writer).write_frame(call_id, KIND_RESPONSE | flags, segs, total)
            await writer.drain()
        except (ConnectionError, RuntimeError):
            pass


_oneway_tasks: set = set()


def _oneway_done(task) -> None:
    _oneway_tasks.discard(task)
    exc = task.exception() if not task.cancelled() else None
    if exc is not None:
        logger.debug("oneway rpc failed: %s", exc)


class RpcClient:
    """Persistent connection with pipelined calls + reconnect/retry."""

    def __init__(self, host: str, port: int, loop_thread: Optional[EventLoopThread] = None):
        self.host = host
        self.port = port
        self._loop_thread = loop_thread or EventLoopThread.get_global()
        self._lock = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._conn_lock: Optional[asyncio.Lock] = None

    # -- async internals --------------------------------------------------
    async def _ensure_connected(self) -> None:
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                timeout=config.rpc_connect_timeout_s,
            )
            self._writer = writer
            self._reader_task = asyncio.ensure_future(self._read_loop(reader))

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                call_id, kind, body = await _read_frame(reader)
                fut = self._pending.pop(call_id, None)
                if fut is not None and not fut.done():
                    fut.set_result((kind, body))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._writer = None
            # teardown must not orphan in-flight response futures: every
            # caller sees ConnectionError, never a silent hang
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(RpcConnectionError(f"connection to {self.host}:{self.port} lost"))
            self._pending.clear()

    async def _call_async(self, method: str, kwargs: dict, oneway: bool, timeout: Optional[float]) -> Any:
        """Must run on self._loop_thread.loop — all connection state
        (writer, pending futures, read loop) is affine to that loop."""
        if timeout is not None and timeout < 0:
            timeout = None  # negative = wait forever (long-running tasks)
        await self._ensure_connected()
        with self._lock:
            self._next_id += 1
            call_id = self._next_id
        if oneway:
            _send_frame(self._writer, call_id, KIND_ONEWAY, (method, kwargs))
            await self._writer.drain()
            return None
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[call_id] = fut
        _send_frame(self._writer, call_id, KIND_REQUEST, (method, kwargs))
        await self._writer.drain()
        kind, body = await asyncio.wait_for(fut, timeout=timeout)
        if len(body) > _LOOP_DECODE_MAX:
            ok, payload = await asyncio.get_event_loop().run_in_executor(
                None, _decode_body, kind, body)
        else:
            ok, payload = _decode_body(kind, body)
        if not ok:
            raise RemoteError(payload)
        return payload

    # -- public sync API --------------------------------------------------
    def call(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        timeout = timeout if timeout is not None else config.rpc_call_timeout_s
        outer = None if timeout < 0 else timeout + 5
        return self._loop_thread.run_coro(
            self._call_async(method, kwargs, oneway=False, timeout=timeout),
            timeout=outer,
        )

    def call_retrying(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        """Retry on connection errors with exponential backoff (reference:
        retryable_grpc_client.h)."""
        delay = config.rpc_retry_base_delay_ms / 1000.0
        last: Optional[Exception] = None
        for _ in range(max(1, config.rpc_max_retries)):
            try:
                return self.call(method, timeout=timeout, **kwargs)
            except (RpcConnectionError, ConnectionError, asyncio.TimeoutError, TimeoutError, OSError) as e:
                last = e
                time.sleep(delay)
                delay = min(delay * 2, config.rpc_retry_max_delay_ms / 1000.0)
        raise RpcConnectionError(f"rpc {method} to {self.host}:{self.port} failed after retries: {last}")

    def call_oneway(self, method: str, **kwargs) -> None:
        coro = self._call_async(method, kwargs, oneway=True, timeout=None)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop_thread.loop:
            # caller IS the io loop (e.g. a refcount release triggered
            # from a dispatcher coroutine): blocking run_coro here would
            # deadlock the loop on itself — fire and forget instead.
            # Pin the task (asyncio holds only weak refs) so GC cannot
            # collect it mid-flight, and drain its exception.
            task = asyncio.ensure_future(coro)
            _oneway_tasks.add(task)
            task.add_done_callback(_oneway_done)
            return
        self._loop_thread.run_coro(coro, timeout=30)

    async def acall(self, method: str, timeout: Optional[float] = None, **kwargs) -> Any:
        """Async call, safe from ANY event loop: the I/O always executes on
        this client's owning loop (cross-loop use of one cached client was a
        silent-hang bug — futures created on loop A resolved from loop B
        never wake A)."""
        timeout = timeout if timeout is not None else config.rpc_call_timeout_s
        running = asyncio.get_event_loop()
        if running is self._loop_thread.loop:
            return await self._call_async(method, kwargs, oneway=False, timeout=timeout)
        cf = asyncio.run_coroutine_threadsafe(
            self._call_async(method, kwargs, oneway=False, timeout=timeout),
            self._loop_thread.loop,
        )
        return await asyncio.wrap_future(cf)

    def close(self) -> None:
        async def _close():
            # cancel AND await the read loop: a merely-closed writer
            # leaves the reader task alive until the loop is torn down,
            # and asyncio then logs "Task was destroyed but it is
            # pending!" at interpreter exit (BENCH r05 finding)
            task, self._reader_task = self._reader_task, None
            w, self._writer = self._writer, None
            if w is not None:
                try:
                    w.close()
                except Exception:  # noqa: BLE001
                    pass
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except BaseException:  # noqa: BLE001 — CancelledError et al.
                    pass
            # the read loop's finally failed in-flight futures; cover the
            # window where close() ran before the loop ever started
            for fut in list(self._pending.values()):
                if not fut.done():
                    fut.set_exception(RpcConnectionError(
                        f"client to {self.host}:{self.port} closed"))
            self._pending.clear()

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop_thread.loop:
            # caller IS the owning loop (a LoopHandle-bound client closed
            # from a gcs/raylet handler): blocking run_coro would deadlock
            # the loop on itself — detach the teardown instead
            task = asyncio.ensure_future(_close())
            _oneway_tasks.add(task)
            task.add_done_callback(_oneway_done)
            return
        try:
            self._loop_thread.run_coro(_close(), timeout=5)
        except Exception:
            # owning loop already stopped at teardown: in-flight futures
            # were failed by the read loop's finally; nothing left to free
            logger.debug("client close to %s:%s failed", self.host,
                         self.port, exc_info=True)


_client_cache: Dict[Tuple[str, int], RpcClient] = {}
# RAY_TPU_DEBUG_LOCKS=1 wraps this (and the other central _private locks)
# in an order-recording proxy that raises on cycle-forming acquisition —
# the dynamic validation of raycheck's static RC002 lock-order model
_client_cache_lock = debug_locks.maybe_wrap(
    threading.Lock(), "rpc._client_cache_lock")


def get_client(addr: Tuple[str, int]) -> RpcClient:
    """Process-wide client cache — one connection per peer."""
    with _client_cache_lock:
        c = _client_cache.get(addr)
        if c is None:
            c = RpcClient(addr[0], addr[1])
            _client_cache[addr] = c
        return c


def clear_client_cache() -> None:
    # Snapshot-then-close: closing INSIDE the lock livelocked shutdown —
    # each close() parks 5s in run_coro while the io loop sits blocked in
    # get_client() on this same lock (observed: a 2,000-actor driver's
    # teardown wedged for hours, 5s per cached client). With the lock
    # dropped first, the loop's get_client proceeds and every close's
    # coroutine actually runs.
    with _client_cache_lock:
        clients = list(_client_cache.values())
        _client_cache.clear()
    for c in clients:
        c.close()
