"""Runtime environments — per-job/task/actor execution environments.

Reference: python/ray/_private/runtime_env/ (plugins: working_dir.py,
py_modules.py, pip.py, ...) and the per-node runtime-env agent. Three
fields are supported natively:

- ``env_vars``: {name: value} exported in the worker before user code,
- ``working_dir``: a local directory, zipped by the driver into the GCS
  KV (content-addressed) and extracted + chdir'd + sys.path'd on the
  worker,
- ``py_modules``: list of local directories, shipped the same way and
  added to sys.path,
- ``pip`` / ``uv``: a list of requirement strings — the worker's node
  builds a virtualenv for that exact requirement set ONCE
  (content-hash-addressed under the node cache, ``uv`` preferred for
  speed, ``--system-site-packages`` so this framework and jax stay
  importable — reference: _private/runtime_env/pip.py:300, uv.py), and
  the worker activates it by prepending its site-packages. Workers are
  dedicated per env hash (raylet pool), so activation never crosses
  envs.

``conda``/``container`` are rejected with a clear error (reference
gates those behind the runtime-env agent + image tooling).

Worker semantics: applying an env marks the worker (env vars stay set,
paths stay on sys.path) — the reference dedicates workers to a runtime
env rather than sandboxing per task, and so do we; application is
idempotent per content hash.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

PKG_NAMESPACE = "runtime_env_packages"
_UNSUPPORTED = ("conda", "container", "image_uri")

# driver-side upload cache: abspath -> (signature, pkg_key)
_upload_cache: Dict[str, Tuple[Tuple, str]] = {}
# worker-side: applied env hashes + extracted package keys
_applied_envs: set = set()
_extracted: Dict[str, str] = {}


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, base))
    return buf.getvalue()


# signature memo: path -> (checked_at, signature). Submitting many
# tasks with the same working_dir must not re-walk the tree every time.
# The TTL bounds staleness for an edit-and-resubmit loop to ~1s (the
# reference uploads working_dir once per JOB, i.e. unbounded staleness).
_SIG_TTL_S = 1.0
_sig_cache: Dict[str, Tuple[float, Tuple]] = {}


def _dir_signature(path: str) -> Tuple:
    """Cheap change detector: (max mtime incl. directories, file count).
    Directory mtimes change on deletion, and the count catches removals
    whose parent-dir mtime granularity misses them."""
    import time as _t

    cached = _sig_cache.get(path)
    now = _t.monotonic()
    if cached and now - cached[0] < _SIG_TTL_S:
        return cached[1]
    mx = os.path.getmtime(path)
    count = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        try:
            mx = max(mx, os.path.getmtime(root))
        except OSError:
            pass
        for f in files:
            count += 1
            try:
                mx = max(mx, os.path.getmtime(os.path.join(root, f)))
            except OSError:
                pass
    sig = (mx, count)
    _sig_cache[path] = (now, sig)
    return sig


def upload_package(gcs, path: str) -> str:
    """Zip ``path`` into the GCS KV; returns the content-addressed key
    (reference: runtime_env packaging.py upload_package_to_gcs)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path!r}")
    sig = _dir_signature(path)
    cached = _upload_cache.get(path)
    if cached and cached[0] == sig:
        return cached[1]
    blob = _zip_dir(path)
    key = "pkg_" + hashlib.sha256(blob).hexdigest()[:20]
    if not gcs.call("KVGet", ns=PKG_NAMESPACE, key=key, timeout=30):
        gcs.call("KVPut", ns=PKG_NAMESPACE, key=key, value=blob,
                 overwrite=True, timeout=60)
    _upload_cache[path] = (sig, key)
    return key


def prepare_runtime_env(env: Optional[Dict[str, Any]], gcs) -> Dict[str, Any]:
    """Driver side: validate + replace local dirs with package keys."""
    if not env:
        return {}
    for k in _UNSUPPORTED:
        if env.get(k):
            raise ValueError(
                f"runtime_env field {k!r} is not supported in this build "
                f"(supported: env_vars, working_dir, py_modules)")
    out: Dict[str, Any] = {}
    if env.get("env_vars"):
        out["env_vars"] = {str(k): str(v)
                           for k, v in env["env_vars"].items()}
    wd = env.get("working_dir")
    if wd:
        out["working_dir_pkg"] = wd if str(wd).startswith("pkg_") \
            else upload_package(gcs, wd)
    for m in env.get("py_modules") or []:
        out.setdefault("py_module_pkgs", []).append(
            m if str(m).startswith("pkg_") else upload_package(gcs, m))
    reqs = env.get("pip") or env.get("uv")
    if reqs:
        if isinstance(reqs, dict):  # reference accepts {"packages": [...]}
            reqs = reqs.get("packages") or []
        if not isinstance(reqs, (list, tuple)):
            raise ValueError("runtime_env pip/uv must be a list of "
                             "requirement strings")
        out["pip_requirements"] = sorted(str(r) for r in reqs)
    return out


def _extract_package(gcs, key: str, cache_dir: str) -> str:
    dest = _extracted.get(key)
    if dest:
        return dest
    dest = os.path.join(cache_dir, key)
    if not os.path.isdir(dest):
        blob = gcs.call("KVGet", ns=PKG_NAMESPACE, key=key, timeout=60)
        if blob is None:
            raise RuntimeError(f"runtime_env package {key} missing from GCS")
        # unique tmp dir per extractor: the cache dir is shared by every
        # worker process on the node, and a shared ".tmp" path would let
        # one extractor rename another's half-written tree into place
        import shutil
        import tempfile as _tf

        tmp = _tf.mkdtemp(prefix=key + ".", dir=cache_dir)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # concurrent winner
    _extracted[key] = dest
    return dest


def _venv_site_packages(venv_dir: str) -> str:
    import glob as _glob

    hits = _glob.glob(os.path.join(venv_dir, "lib", "python*",
                                   "site-packages"))
    if not hits:
        raise RuntimeError(f"no site-packages under venv {venv_dir}")
    return hits[0]


def build_pip_venv(requirements: List[str], cache_dir: str) -> str:
    """Build (or reuse) the virtualenv for an exact requirement set.

    Content-hash-addressed: every worker on the node asking for the
    same sorted requirement list shares one venv; concurrent builders
    race benignly (build into a private tmp dir, atomic rename, loser
    discards). ``uv`` is used when present (reference: uv.py — an
    order of magnitude faster than pip), else ``python -m venv`` +
    pip. ``--system-site-packages`` keeps this framework and its deps
    importable from inside the env, like the reference's pip plugin
    (reference: _private/runtime_env/pip.py:300 _install_pip_packages).

    Returns the venv's site-packages path.
    """
    import shutil
    import subprocess
    import tempfile as _tf

    key = "venv_" + hashlib.sha256(
        "\n".join(requirements).encode()).hexdigest()[:20]
    dest = os.path.join(cache_dir, key)
    if os.path.isdir(dest):
        return _venv_site_packages(dest)
    os.makedirs(cache_dir, exist_ok=True)
    tmp = _tf.mkdtemp(prefix=key + ".", dir=cache_dir)
    try:
        uv = shutil.which("uv")
        # bounded: a hung index connection must fail the TASK, not wedge
        # the env-dedicated worker (and every task queued on its env
        # hash) forever
        build_timeout = 600
        if uv:
            subprocess.run(
                [uv, "venv", "--system-site-packages", "--python",
                 sys.executable, tmp],
                check=True, capture_output=True, text=True,
                timeout=build_timeout)
            install = [uv, "pip", "install", "--python",
                       os.path.join(tmp, "bin", "python")]
        else:
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 tmp],
                check=True, capture_output=True, text=True,
                timeout=build_timeout)
            install = [os.path.join(tmp, "bin", "python"), "-m", "pip",
                       "install", "--no-input"]
        proc = subprocess.run(install + list(requirements),
                              capture_output=True, text=True,
                              timeout=build_timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"runtime_env pip install failed:\n{proc.stdout}\n"
                f"{proc.stderr}")
        try:
            os.rename(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # concurrent winner
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return _venv_site_packages(dest)


def env_hash(env: Dict[str, Any]) -> str:
    import json

    return hashlib.sha256(
        json.dumps(env, sort_keys=True, default=str).encode()).hexdigest()


def apply_runtime_env(env: Optional[Dict[str, Any]], gcs,
                      cache_dir: str) -> None:
    """Worker side: idempotently apply a PREPARED runtime env."""
    if not env:
        return
    h = env_hash(env)
    if h in _applied_envs:
        return
    for k, v in (env.get("env_vars") or {}).items():
        os.environ[k] = v
    reqs = env.get("pip_requirements")
    if reqs:
        sp = build_pip_venv(list(reqs),
                            os.path.join(cache_dir, "venvs"))
        if sp not in sys.path:
            sys.path.insert(0, sp)
    for key in env.get("py_module_pkgs") or []:
        p = _extract_package(gcs, key, cache_dir)
        if p not in sys.path:
            sys.path.insert(0, p)
    wd_key = env.get("working_dir_pkg")
    if wd_key:
        p = _extract_package(gcs, wd_key, cache_dir)
        if p not in sys.path:
            sys.path.insert(0, p)
        os.chdir(p)
    _applied_envs.add(h)


def merge_runtime_envs(job_env: Optional[Dict[str, Any]],
                       task_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Task env overrides job env; env_vars merge key-wise (reference:
    runtime_env merge semantics, _private/runtime_env/merge.py)."""
    job_env = job_env or {}
    task_env = task_env or {}
    out = dict(job_env)
    for k, v in task_env.items():
        if k == "env_vars":
            merged = dict(job_env.get("env_vars") or {})
            merged.update(v or {})
            out["env_vars"] = merged
        else:
            out[k] = v
    return out
