"""Serialization of task args / return values / put objects.

Reference: python/ray/_private/serialization.py. Uses cloudpickle with
pickle-protocol-5 out-of-band buffers so large numpy / jax host arrays are
captured as contiguous buffers (zero-copy into/out of the shared-memory
object store), plus a per-job custom-serializer registry.

Wire format of a serialized object:
    [u32 meta_len][meta pickle][u32 nbuffers][u64 len, bytes]...
where meta is the cloudpickle payload with PickleBuffer placeholders.
"""

from __future__ import annotations

import io
import pickle
import struct
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

_custom_serializers: Dict[type, Tuple[Callable, Callable]] = {}
_lock = threading.Lock()

# Thread-local collector: while active, every ObjectRef pickled through
# serialize() is recorded so callers can pin/borrow-register contained
# refs (reference: nested-ref tracking in reference_counter.h:44).
_collect_ctx = threading.local()


class collect_object_refs:
    """Context manager; exposes `.refs` — the list of ObjectRefs that were
    serialized (nested at any depth) while active on this thread."""

    def __enter__(self):
        self._prev = getattr(_collect_ctx, "refs", None)
        _collect_ctx.refs = self.refs = []
        return self

    def __exit__(self, *exc):
        _collect_ctx.refs = self._prev
        return False


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable) -> None:
    """Register a custom (de)serializer pair (reference:
    ray.util.register_serializer)."""
    with _lock:
        _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type) -> None:
    with _lock:
        _custom_serializers.pop(cls, None)


class _CustomPickler(cloudpickle.Pickler):
    def __init__(self, file, protocol=5, buffer_callback=None):
        super().__init__(file, protocol=protocol, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            refs = getattr(_collect_ctx, "refs", None)
            if refs is not None:
                refs.append(obj)
            return obj.__reduce__()
        s = _custom_serializers.get(type(obj))
        if s is not None:
            ser, deser = s
            return (_reconstruct_custom, (type(obj).__module__, type(obj).__qualname__, ser(obj)))
        # Delegate to cloudpickle's reducer_override — that is where its
        # pickle-functions/classes-by-value logic lives; returning
        # NotImplemented here would silently downgrade to plain pickle.
        return super().reducer_override(obj)


def _reconstruct_custom(module: str, qualname: str, payload: Any):
    import importlib

    mod = importlib.import_module(module)
    cls = mod
    for part in qualname.split("."):
        cls = getattr(cls, part)
    _, deser = _custom_serializers[cls]
    return deser(payload)


def _device_to_host(obj: Any) -> Any:
    """jax.Array values are pulled to host before pickling."""
    return obj


def serialize(value: Any) -> bytes:
    """Serialize a Python value into the wire/object-store format."""
    buffers: List[pickle.PickleBuffer] = []
    bio = io.BytesIO()
    pickler = _CustomPickler(bio, protocol=5, buffer_callback=buffers.append)
    pickler.dump(value)
    meta = bio.getvalue()
    out = io.BytesIO()
    out.write(struct.pack("<I", len(meta)))
    out.write(meta)
    out.write(struct.pack("<I", len(buffers)))
    for b in buffers:
        raw = b.raw()
        out.write(struct.pack("<Q", raw.nbytes))
        out.write(raw)
        b.release()
    return out.getvalue()


def serialize_into(value: Any, alloc: Callable[[int], memoryview]) -> memoryview:
    """Serialize directly into store-provided memory (one copy, no interim
    bytes join for the buffer region when possible)."""
    data = serialize(value)
    mv = alloc(len(data))
    mv[: len(data)] = data
    return mv


# Python-level buffer protocol (PEP 688 ``__buffer__``) only exists on
# 3.12+. Earlier interpreters can't hand consumers like np.frombuffer a
# trackable zero-copy wrapper, so they copy out-of-band buffers and
# release the store pin immediately (deserialize() below).
_HAS_PEP688 = sys.version_info >= (3, 12)


class _TrackedBuffer:
    """Buffer-protocol wrapper (PEP 688) around a shared-memory slice.

    Zero-copy deserialized arrays keep their exporter alive through the
    buffer protocol; when the LAST tracked buffer of a deserialize() call
    is garbage-collected, the shared release callback fires — that is how
    a store get-pin lives exactly as long as the values viewing it
    (reference: plasma client buffer lifetime, plasma/client.h:261)."""

    __slots__ = ("_mv", "_shared")

    def __init__(self, mv: memoryview, shared: list):
        self._mv = mv
        self._shared = shared
        with shared[2]:
            shared[0] += 1

    def __buffer__(self, flags):
        return memoryview(self._mv)

    def __del__(self):
        s = self._shared
        cb = None
        with s[2]:  # __del__ may run concurrently on different threads
            s[0] -= 1
            if s[0] == 0 and s[1] is not None:
                cb, s[1] = s[1], None
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — GC context
                pass


def deserialize(data: "bytes | memoryview", release_cb: Optional[Callable] = None) -> Any:
    """Deserialize the wire format. With ``release_cb``, out-of-band buffers
    are zero-copy views and the callback fires once every reconstructed
    value viewing them has been collected (pin-for-value-lifetime)."""
    shared = [0, release_cb, threading.Lock()]
    try:
        mv = memoryview(data)
        (meta_len,) = struct.unpack_from("<I", mv, 0)
        off = 4
        meta = mv[off : off + meta_len]
        off += meta_len
        (nbuf,) = struct.unpack_from("<I", mv, off)
        off += 4
        buffers = []
        for _ in range(nbuf):
            (blen,) = struct.unpack_from("<Q", mv, off)
            off += 8
            sl = mv[off : off + blen]  # zero-copy view
            if release_cb is None:
                buffers.append(sl)
            elif _HAS_PEP688:
                buffers.append(_TrackedBuffer(sl, shared))
            else:
                # pre-3.12: no Python-visible buffer protocol, so a
                # tracked zero-copy wrapper is invisible to consumers
                # (np.frombuffer raises). Copy the slice; the pin then
                # releases in the finally below instead of at value GC.
                buffers.append(bytes(sl))
            off += blen
        return pickle.loads(
            bytes(meta) if isinstance(meta, memoryview) else meta, buffers=buffers
        )
    finally:
        # no tracked buffer exists (none created, or creation failed):
        # nothing views the region, release now. Otherwise the buffers'
        # GC fires the shared callback.
        if release_cb is not None:
            fire = None
            with shared[2]:
                if shared[0] == 0 and shared[1] is not None:
                    fire, shared[1] = shared[1], None
            if fire is not None:
                try:
                    fire()
                except Exception:  # noqa: BLE001
                    pass


def dumps_function(fn: Any) -> bytes:
    return cloudpickle.dumps(fn, protocol=5)


def loads_function(data: bytes) -> Any:
    return cloudpickle.loads(data)
