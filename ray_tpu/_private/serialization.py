"""Serialization of task args / return values / put objects.

Reference: python/ray/_private/serialization.py. Uses cloudpickle with
pickle-protocol-5 out-of-band buffers so large numpy / jax host arrays are
captured as contiguous buffers (zero-copy into/out of the shared-memory
object store), plus a per-job custom-serializer registry.

Wire format of a serialized object:
    [u32 meta_len][meta pickle][u32 nbuffers][u64 len, bytes]...
where meta is the cloudpickle payload with PickleBuffer placeholders.

Zero-copy data plane: ``serialize_prepare`` pickles the value ONCE into a
small meta blob plus borrowed views of the out-of-band payload buffers,
and ``SerializedValue.write_into`` lays the wire format straight into a
caller-provided mapping (the plasma Create→write-in-place→Seal path) —
payload bytes move exactly once, source array → shared memory.  Every
INTERMEDIATE payload materialization (the legacy bytes-joining
``serialize``, the pre-3.12 copy-out in ``deserialize``) is recorded in a
process-local copy counter exported on the metrics scrape
(``ray_tpu_payload_copies``), so "0 payload copies on the put path" is a
testable invariant, not a code-review claim.
"""

from __future__ import annotations

import io
import pickle
import struct
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import fastpath

_custom_serializers: Dict[type, Tuple[Callable, Callable]] = {}
_lock = threading.Lock()

# ---------------------------------------------------------------------------
# Payload-copy accounting. Counts INTERMEDIATE materializations of
# out-of-band payload bytes (joins into temporary bytes objects, copy-outs
# of shared-memory views) — NOT the single unavoidable write into the
# destination mapping/socket. The zero-copy put path must keep the "put"
# series at zero; tests assert on deltas of ``copy_stats()``.
# ---------------------------------------------------------------------------
_copy_lock = threading.Lock()
# "put" = the plasma zero-copy path (must stay 0); "inline" = joins of
# sub-threshold values bound for the in-memory store (expected, small);
# "get" = deserialize copy-outs; "rpc" = RPC body materializations
_copy_counts: Dict[str, int] = {"put": 0, "inline": 0, "get": 0, "rpc": 0}
_copy_bytes: Dict[str, int] = {"put": 0, "inline": 0, "get": 0, "rpc": 0}
_copy_metrics_registered = False


def record_payload_copy(path: str, nbytes: int, n: int = 1) -> None:
    """Record ``n`` intermediate payload copies totalling ``nbytes`` on a
    data-plane path ("put" | "get" | "rpc")."""
    with _copy_lock:
        _copy_counts[path] = _copy_counts.get(path, 0) + n
        _copy_bytes[path] = _copy_bytes.get(path, 0) + nbytes


def copy_stats() -> Dict[str, Dict[str, int]]:
    """Snapshot of the process-local payload-copy counters."""
    with _copy_lock:
        return {
            "copies": dict(_copy_counts),
            "bytes": dict(_copy_bytes),
        }


def _ensure_copy_metrics() -> None:
    """Register the copy counters on the metrics scrape, once. Lazy (first
    data-plane use) so importing this module never spawns the pusher."""
    global _copy_metrics_registered
    if _copy_metrics_registered:
        return
    _copy_metrics_registered = True
    try:
        from ray_tpu.util.metrics import Metric

        class _CopyCounter(Metric):
            """Live view over the module counters: zero hot-path cost —
            the registry reads the dicts only at snapshot time."""

            def __init__(self, name: str, values: Dict[str, int],
                         description: str):
                super().__init__(name, description, tag_keys=("path",))
                self._live = values

            def _snapshot(self) -> dict:
                with _copy_lock:
                    series = [{"tags": {"path": k}, "value": float(v)}
                              for k, v in self._live.items()]
                return {"name": self._name, "type": "counter",
                        "description": self._description, "series": series}

        _CopyCounter(
            "ray_tpu_payload_copies", _copy_counts,
            "Intermediate payload-byte copies on the data plane")
        _CopyCounter(
            "ray_tpu_payload_copy_bytes", _copy_bytes,
            "Intermediate payload bytes copied on the data plane")
    except Exception:  # noqa: BLE001 — metrics must never break the data plane
        pass

# Thread-local collector: while active, every ObjectRef pickled through
# serialize() is recorded so callers can pin/borrow-register contained
# refs (reference: nested-ref tracking in reference_counter.h:44).
_collect_ctx = threading.local()


class collect_object_refs:
    """Context manager; exposes `.refs` — the list of ObjectRefs that were
    serialized (nested at any depth) while active on this thread."""

    def __enter__(self):
        self._prev = getattr(_collect_ctx, "refs", None)
        _collect_ctx.refs = self.refs = []
        return self

    def __exit__(self, *exc):
        _collect_ctx.refs = self._prev
        return False


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable) -> None:
    """Register a custom (de)serializer pair (reference:
    ray.util.register_serializer)."""
    with _lock:
        _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type) -> None:
    with _lock:
        _custom_serializers.pop(cls, None)


class _CustomPickler(cloudpickle.Pickler):
    def __init__(self, file, protocol=5, buffer_callback=None):
        super().__init__(file, protocol=protocol, buffer_callback=buffer_callback)

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            refs = getattr(_collect_ctx, "refs", None)
            if refs is not None:
                refs.append(obj)
            return obj.__reduce__()
        s = _custom_serializers.get(type(obj))
        if s is not None:
            ser, deser = s
            return (_reconstruct_custom, (type(obj).__module__, type(obj).__qualname__, ser(obj)))
        # Delegate to cloudpickle's reducer_override — that is where its
        # pickle-functions/classes-by-value logic lives; returning
        # NotImplemented here would silently downgrade to plain pickle.
        return super().reducer_override(obj)


def _reconstruct_custom(module: str, qualname: str, payload: Any):
    import importlib

    mod = importlib.import_module(module)
    cls = mod
    for part in qualname.split("."):
        cls = getattr(cls, part)
    _, deser = _custom_serializers[cls]
    return deser(payload)


def _device_to_host(obj: Any) -> Any:
    """jax.Array values are pulled to host before pickling."""
    return obj


class SerializedValue:
    """The two-phase serialization handle: pickled meta plus BORROWED
    zero-copy views of the out-of-band payload buffers (they alias the
    caller's live arrays — write/consume before mutating the source).

    ``write_into`` lays the wire format into a destination mapping in one
    pass (the plasma write-in-place path); ``segments`` exposes the frame
    as a list of buffer segments for vectored socket writes; ``to_bytes``
    is the counted legacy join."""

    __slots__ = ("meta", "_pickle_buffers", "buffers", "total")

    def __init__(self, meta: bytes, pickle_buffers: List[pickle.PickleBuffer]):
        self.meta = meta
        self._pickle_buffers = pickle_buffers
        self.buffers: List[memoryview] = []
        total = 8 + len(meta)
        for b in pickle_buffers:
            raw = b.raw()
            if raw.ndim != 1 or raw.format != "B":
                raw = raw.cast("B")
            self.buffers.append(raw)
            total += 8 + raw.nbytes
        self.total = total

    @property
    def payload_nbytes(self) -> int:
        return sum(b.nbytes for b in self.buffers)

    def segments(self) -> List["bytes | memoryview"]:
        """The wire frame as an ordered list of buffer segments (no
        payload concatenation)."""
        segs: List[Any] = [
            struct.pack("<I", len(self.meta)),
            self.meta,
            struct.pack("<I", len(self.buffers)),
        ]
        for b in self.buffers:
            segs.append(struct.pack("<Q", b.nbytes))
            segs.append(b)
        return segs

    def write_into(self, dest: memoryview) -> int:
        """Single-pass copy-free layout into ``dest`` (length >= .total):
        payload bytes move exactly once, source buffer → dest. Runs on the
        fastpath codec — the C backend releases the GIL around the payload
        memcpy, so a multi-MB put no longer stalls sibling threads.
        Returns the number of bytes written."""
        return fastpath.write_body_into(dest, self.meta, self.buffers)

    def to_bytes(self, copy_path: Optional[str] = "put") -> bytes:
        """Materialize the frame as one bytes object (the legacy join) —
        counted as an intermediate payload copy when out-of-band buffers
        exist."""
        payload = self.payload_nbytes
        if payload and copy_path:
            record_payload_copy(copy_path, payload, n=len(self.buffers))
        out = bytearray(self.total)
        self.write_into(memoryview(out))
        return bytes(out)

    def release(self) -> None:
        """Release the borrowed buffer views (call after the frame has
        been written; the handle must not be used afterwards)."""
        for mv in self.buffers:
            try:
                mv.release()
            except Exception:  # noqa: BLE001
                pass
        self.buffers = []
        for b in self._pickle_buffers:
            try:
                b.release()
            except Exception:  # noqa: BLE001
                pass
        self._pickle_buffers = []


def serialize_prepare(value: Any) -> SerializedValue:
    """Phase one of the zero-copy put path: pickle once, keep the payload
    as borrowed views instead of joining bytes."""
    _ensure_copy_metrics()
    buffers: List[pickle.PickleBuffer] = []
    bio = io.BytesIO()
    pickler = _CustomPickler(bio, protocol=5, buffer_callback=buffers.append)
    pickler.dump(value)
    return SerializedValue(bio.getvalue(), buffers)


def serialize(value: Any) -> bytes:
    """Serialize a Python value into the wire/object-store format as one
    bytes object (joins out-of-band payload — counted; prefer
    ``serialize_prepare`` + ``write_into`` on hot paths)."""
    sv = serialize_prepare(value)
    try:
        return sv.to_bytes()
    finally:
        sv.release()


def serialize_into(value: Any, alloc: Callable[[int], memoryview]) -> memoryview:
    """Serialize directly into store-provided memory: the allocation is
    sized AFTER pickling (phase one), then payload bytes move exactly once
    into the provided mapping."""
    sv = serialize_prepare(value)
    try:
        mv = alloc(sv.total)
        sv.write_into(mv)
        return mv
    finally:
        sv.release()


# Python-level buffer protocol (PEP 688 ``__buffer__``) only exists on
# 3.12+. Earlier interpreters can't hand consumers like np.frombuffer a
# trackable zero-copy wrapper, so they copy out-of-band buffers and
# release the store pin immediately (deserialize() below).
_HAS_PEP688 = sys.version_info >= (3, 12)


class _TrackedBuffer:
    """Buffer-protocol wrapper (PEP 688) around a shared-memory slice.

    Zero-copy deserialized arrays keep their exporter alive through the
    buffer protocol; when the LAST tracked buffer of a deserialize() call
    is garbage-collected, the shared release callback fires — that is how
    a store get-pin lives exactly as long as the values viewing it
    (reference: plasma client buffer lifetime, plasma/client.h:261)."""

    __slots__ = ("_mv", "_shared")

    def __init__(self, mv: memoryview, shared: list):
        self._mv = mv
        self._shared = shared
        with shared[2]:
            shared[0] += 1

    def __buffer__(self, flags):
        return memoryview(self._mv)

    def __del__(self):
        s = self._shared
        cb = None
        with s[2]:  # __del__ may run concurrently on different threads
            s[0] -= 1
            if s[0] == 0 and s[1] is not None:
                cb, s[1] = s[1], None
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — GC context
                pass


def deserialize(data: "bytes | memoryview", release_cb: Optional[Callable] = None) -> Any:
    """Deserialize the wire format. With ``release_cb``, out-of-band buffers
    are zero-copy views and the callback fires once every reconstructed
    value viewing them has been collected (pin-for-value-lifetime)."""
    shared = [0, release_cb, threading.Lock()]
    try:
        meta, raw_buffers = fastpath.decode_body(data)
        buffers = []
        for sl in raw_buffers:
            if release_cb is None:
                buffers.append(sl)  # zero-copy view
            elif _HAS_PEP688:
                buffers.append(_TrackedBuffer(sl, shared))
            else:
                # pre-3.12: no Python-visible buffer protocol, so a
                # tracked zero-copy wrapper is invisible to consumers
                # (np.frombuffer raises). Copy the slice; the pin then
                # releases in the finally below instead of at value GC.
                record_payload_copy("get", sl.nbytes)
                buffers.append(bytes(sl))
        return pickle.loads(
            bytes(meta) if isinstance(meta, memoryview) else meta, buffers=buffers
        )
    finally:
        # no tracked buffer exists (none created, or creation failed):
        # nothing views the region, release now. Otherwise the buffers'
        # GC fires the shared callback.
        if release_cb is not None:
            fire = None
            with shared[2]:
                if shared[0] == 0 and shared[1] is not None:
                    fire, shared[1] = shared[1], None
            if fire is not None:
                try:
                    fire()
                except Exception:  # noqa: BLE001
                    pass


def dumps_function(fn: Any) -> bytes:
    return cloudpickle.dumps(fn, protocol=5)


def loads_function(data: bytes) -> Any:
    return cloudpickle.loads(data)
