"""Streaming generators: consume a task's yields while it still runs.

Reference: streaming-generator returns in src/ray/core_worker/
task_manager.cc:778 (HandleReportGeneratorItemReturns) and
python/ray/_raylet.pyx ObjectRefGenerator — re-designed for the pickle-RPC
runtime: the executing worker pushes one ``StreamingYield`` RPC per yielded
value to the caller (inline payload or a plasma location), then
``StreamingDone``; the caller-side ``ObjectRefGenerator`` hands out
ObjectRefs in yield order as they arrive.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.exceptions import GetTimeoutError

if TYPE_CHECKING:  # pragma: no cover
    from ray_tpu._private.core_worker import CoreWorker


class StreamEnd(Exception):
    """Async end-of-stream marker: ``anext_ref`` cannot raise
    StopIteration (PEP 479 turns it into a bare RuntimeError inside a
    coroutine), so exhaustion surfaces as this instead."""


class _StreamState:
    """Caller-side bookkeeping for one streaming task."""

    def __init__(self) -> None:
        self.cv = threading.Condition()
        self.arrived: Dict[int, ObjectID] = {}  # yield index -> oid
        self.next_index = 0  # next index to hand to the consumer
        self.total: Optional[int] = None  # set by StreamingDone
        self.error: Optional[BaseException] = None
        # async consumers (the serve proxy loop) park a thread-safe
        # waker here instead of blocking a thread on the cv; fired on
        # every state change alongside the cv notify
        self.wakers: List[Callable[[], None]] = []

    def notify_locked(self) -> None:
        """State changed (yield arrived / done / error / abandon): wake
        every consumer. Must be called with ``cv`` held. Wakers are
        drained — an async consumer re-registers per wait."""
        self.cv.notify_all()
        wakers, self.wakers = self.wakers, []
        for w in wakers:
            try:
                w()
            except Exception:  # noqa: BLE001 — a dead consumer loop
                pass  # must not break delivery to the live ones


class ObjectRefGenerator:
    """Iterator over a streaming task's yields (reference:
    python/ray/_raylet.pyx ObjectRefGenerator). Each ``__next__`` returns
    an ObjectRef as soon as that yield has been produced — the task may
    still be running."""

    def __init__(self, core: "CoreWorker", task_id: TaskID, state: _StreamState):
        self._core = core
        self._task_id = task_id
        self._state = state
        self._close_cb = None
        self._close_fired = False

    def _set_close_callback(self, cb) -> None:
        """Invoked exactly once when the stream terminates (exhausted,
        errored, or dropped) — e.g. Serve uses it to release the routing
        slot the stream occupies."""
        self._close_cb = cb

    def _fire_close(self) -> None:
        if self._close_fired:
            return
        self._close_fired = True
        if self._close_cb is not None:
            try:
                self._close_cb()
            except Exception:  # noqa: BLE001
                pass

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self._next(timeout=None)

    def next_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """Like ``next()`` but with a timeout (raises GetTimeoutError)."""
        return self._next(timeout=timeout)

    def _take_locked(self) -> Optional[ObjectRef]:
        """One non-blocking state inspection (``st.cv`` held): returns
        the next ref, raises the stream's terminal error/StopIteration,
        or returns None when the consumer must wait."""
        st = self._state
        if st.next_index in st.arrived:
            oid = st.arrived.pop(st.next_index)
            st.next_index += 1
            return ObjectRef(oid, owner_addr=self._core.address)
        if st.error is not None:
            self._core._streams.pop(self._task_id, None)
            self._fire_close()
            raise st.error
        if st.total is not None and st.next_index >= st.total:
            self._core._streams.pop(self._task_id, None)
            self._fire_close()
            raise StopIteration
        return None

    def _next(self, timeout: Optional[float]) -> ObjectRef:
        st = self._state
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cv:
            while True:
                ref = self._take_locked()
                if ref is not None:
                    return ref
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"no yield from streaming task {self._task_id.hex()[:12]} in time"
                    )
                st.cv.wait(timeout=remaining if remaining is not None else 1.0)

    async def anext_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """Async ``next_ref``: waits on the consumer's event loop without
        parking a thread per stream (the serve proxy serves hundreds of
        concurrent streams off one loop). Raises GetTimeoutError on
        timeout and :class:`StreamEnd` on exhaustion (StopIteration
        cannot cross a coroutine boundary)."""
        import asyncio

        st = self._state
        loop = asyncio.get_event_loop()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with st.cv:
                try:
                    ref = self._take_locked()
                except StopIteration:
                    raise StreamEnd() from None
                if ref is not None:
                    return ref
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"no yield from streaming task "
                        f"{self._task_id.hex()[:12]} in time")
                fut = loop.create_future()

                def _wake(fut=fut):
                    def _set():
                        if not fut.done():
                            fut.set_result(True)
                    loop.call_soon_threadsafe(_set)

                st.wakers.append(_wake)
            try:
                # bounded re-check even with no deadline: a waker lost to
                # a dying producer must not hang the consumer forever
                await asyncio.wait_for(
                    fut, timeout=min(remaining, 1.0)
                    if remaining is not None else 1.0)
            except asyncio.TimeoutError:
                pass  # loop re-checks state / deadline

    def completed(self) -> bool:
        st = self._state
        with st.cv:
            return st.error is not None or (
                st.total is not None and st.next_index >= st.total
            )

    def __del__(self):
        # dropping the generator abandons the stream: undelivered yields
        # are freed and the producer's next push is refused (the worker
        # then stops producing) — without this a dropped generator pins
        # every yield for the life of the driver
        try:
            self._fire_close()
            abandon = getattr(self._core, "_abandon_stream", None)
            if abandon is not None:
                abandon(self._task_id)
        except Exception:  # noqa: BLE001 — GC context
            pass

    def __repr__(self) -> str:
        return f"ObjectRefGenerator(task={self._task_id.hex()[:12]})"
