"""Task specifications — the unit handed from submitter to scheduler to executor.

Reference: src/ray/common/task/task_spec.h:82 (TaskSpecification) and
src/ray/protobuf/common.proto (TaskSpec message). We keep the same logical
fields (ids, function descriptor, args, resources, retry policy, scheduling
strategy, actor linkage) as a plain dataclass serialized with pickle over our
RPC layer.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2
    DRIVER_TASK = 3


@dataclass
class FunctionDescriptor:
    """Identifies a remote function/class (reference:
    src/ray/common/function_descriptor.h)."""

    module_name: str
    function_name: str
    class_name: str = ""
    function_hash: str = ""

    @property
    def repr_name(self) -> str:
        if self.class_name:
            return f"{self.class_name}.{self.function_name}"
        return self.function_name

    def key(self) -> str:
        return f"{self.module_name}:{self.class_name}:{self.function_name}:{self.function_hash}"


@dataclass
class TaskArg:
    """Either an inline serialized value or an ObjectRef passed by reference."""

    is_ref: bool
    # for by-value: serialized bytes (SerializedObject); for by-ref: object id
    value: Any = None
    object_id: Optional[ObjectID] = None
    owner_addr: Optional[Tuple[str, int]] = None


@dataclass
class SchedulingStrategy:
    """DEFAULT | SPREAD | node-affinity | placement-group (reference:
    python/ray/util/scheduling_strategies.py)."""

    kind: str = "DEFAULT"  # DEFAULT, SPREAD, NODE_AFFINITY, NODE_LABEL, PLACEMENT_GROUP
    node_id: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[str] = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False
    node_labels: Optional[Dict[str, str]] = None


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    function_descriptor: FunctionDescriptor
    language: str = "python"
    args: List[TaskArg] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    # ownership
    caller_id: Optional[WorkerID] = None
    caller_addr: Optional[Tuple[str, int]] = None
    # actor linkage
    actor_id: Optional[ActorID] = None
    actor_creation_id: Optional[ActorID] = None  # set on creation tasks
    max_restarts: int = 0
    max_task_retries: int = 0
    sequence_number: int = 0  # actor task ordering
    concurrency_group: str = ""
    max_concurrency: int = 1
    is_asyncio: bool = False
    # runtime env / function payload
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    serialized_function: Optional[bytes] = None  # inline-shipped function, small fns
    function_key: Optional[str] = None  # GCS KV key for exported functions
    # generators
    is_streaming_generator: bool = False
    # depth for scheduling-class / dedup
    attempt_number: int = 0

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.from_index(self.task_id, i + 1) for i in range(self.num_returns)]

    @property
    def scheduling_class(self) -> Tuple:
        """Group tasks by (fn, resources, runtime env) for lease reuse
        (reference: SchedulingClass in src/ray/common/task/task_spec.h —
        the reference's class includes the runtime env so leased workers
        are never shared across envs).

        Computed once per spec and cached: the submit path reads it
        several times per task (submit, lease lookup, queue keying), and
        its inputs (descriptor, resources, strategy, runtime env) are
        fixed at construction — only attempt_number mutates later."""
        cached = self.__dict__.get("_scheduling_class_cache")
        if cached is not None:
            return cached
        st = self.scheduling_strategy
        self.__dict__["_scheduling_class_cache"] = cached = (
            self.function_descriptor.key(),
            tuple(sorted(self.resources.items())),
            st.kind,
            st.placement_group_id,
            st.placement_group_bundle_index,
            # affinity/label targets must not share leases across targets
            st.node_id,
            tuple(sorted((st.node_labels or {}).items())),
            self.runtime_env_hash(),
        )
        return cached

    def runtime_env_hash(self) -> str:
        if not self.runtime_env:
            return ""
        from ray_tpu._private.runtime_env import env_hash

        return env_hash(self.runtime_env)
