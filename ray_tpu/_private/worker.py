"""The per-process Worker singleton and the init/shutdown/get/put/wait API.

Reference: python/ray/_private/worker.py (Worker :442, init :1438,
connect :2026, shutdown :2069, get/put/wait :2841+). The Worker binds the
public API to a CoreRuntime backend (local-mode or cluster) and holds
per-process state: ids, reference counter, serialization, task context.
"""

from __future__ import annotations

import atexit
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private.config import config
from ray_tpu._private.ids import ActorID, JobID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.reference_counter import ReferenceCounter

logger = logging.getLogger(__name__)

SCRIPT_MODE = "SCRIPT_MODE"
WORKER_MODE = "WORKER_MODE"
LOCAL_MODE = "LOCAL_MODE"


class Worker:
    def __init__(self) -> None:
        self.mode: Optional[str] = None
        self.core = None  # CoreRuntime
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.from_int(0)
        self.reference_counter = ReferenceCounter()
        self.current_task_id = TaskID.for_normal_task(self.job_id)
        self.current_actor_id: Optional[ActorID] = None
        self.current_node_id = None
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._task_context = threading.local()

    @property
    def connected(self) -> bool:
        return self.core is not None

    def next_put_index(self) -> int:
        # put object indices are negative-range in the reference; we use a
        # high offset so they never collide with return indices.
        with self._put_lock:
            self._put_index += 1
            return 1_000_000 + self._put_index

    # task-execution context (set by the executor around user code)
    def set_task_context(self, task_id: TaskID, actor_id: Optional[ActorID] = None) -> None:
        self._task_context.task_id = task_id
        self._task_context.actor_id = actor_id

    def get_task_context(self):
        tid = getattr(self._task_context, "task_id", None)
        aid = getattr(self._task_context, "actor_id", None)
        return tid, aid


global_worker: Optional[Worker] = None
_init_lock = threading.Lock()
# set while no teardown is in flight: shutdown() clears it before the
# slow lock-free teardown and sets it when done, so a concurrent init()
# waits for the old runtime's client-cache sweep instead of having its
# fresh RPC clients closed out from under it
_teardown_done = threading.Event()
_teardown_done.set()


def _require_connected() -> Worker:
    if global_worker is None or not global_worker.connected:
        raise RuntimeError(
            "ray_tpu.init() must be called before using the API "
            "(or set RAY_TPU_AUTO_INIT=1)."
        )
    return global_worker


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    local_mode: bool = False,
    object_store_memory: Optional[int] = None,
    dashboard: bool = False,
    namespace: Optional[str] = None,
    runtime_env: Optional[Dict[str, Any]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
) -> Dict[str, Any]:
    """Start (or connect to) a ray_tpu runtime.

    - ``local_mode=True``: in-process threads (fast tests / debugging).
    - ``address=None``: start a new single-node cluster (GCS + raylet +
      shared-memory object store as child processes) and connect as driver.
    - ``address="<host:port>"``: connect as driver to an existing cluster.
    - ``address="auto"``: discover a running local cluster.
    """
    global global_worker
    with _init_lock:
        # serialize against an in-flight shutdown() teardown (which runs
        # outside _init_lock — see shutdown's RC002 note). Waiting UNDER
        # the lock is deadlock-free (the event's setter never takes the
        # lock) and closes the check-then-act gap a pre-lock wait would
        # leave; bounded by the timeout — raycheck: disable=RC002
        if not _teardown_done.wait(timeout=60):
            logger.warning(
                "previous runtime teardown still in flight after 60s; "
                "proceeding with init (old client-cache sweep may race "
                "this session's fresh connections)")
        if global_worker is not None and global_worker.connected:
            if ignore_reinit_error:
                return {"already_initialized": True}
            raise RuntimeError("ray_tpu.init() called twice; use ignore_reinit_error=True")

        config.initialize(_system_config)
        w = Worker()
        w.mode = LOCAL_MODE if local_mode else SCRIPT_MODE

        if local_mode:
            from ray_tpu._private.local_mode import LocalModeRuntime

            w.core = LocalModeRuntime(resources=resources, num_cpus=num_cpus or 8)
        elif address and str(address).startswith("ray://"):
            # remote driver over TCP (reference: ray client, util/client/):
            # the whole CoreRuntime proxies to a head-side ClientServer
            from ray_tpu.util.client import ClientRuntime

            w.core = ClientRuntime(str(address)[len("ray://"):])
            w.core.job_runtime_env = runtime_env or {}
        else:
            from ray_tpu._private.cluster_runtime import ClusterRuntime

            w.core = ClusterRuntime.create(
                address=address,
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
                object_store_memory=object_store_memory,
                namespace=namespace,
                dashboard=dashboard,
            )
            w.job_id = w.core.job_id
            # job-level runtime env: merged under every task/actor env
            w.core.job_runtime_env = runtime_env or {}
        if local_mode and runtime_env:
            # in-process execution: env_vars apply directly; packaged
            # fields are meaningless without worker processes
            import os as _os

            for k, v in (runtime_env.get("env_vars") or {}).items():
                _os.environ[str(k)] = str(v)
        w.reference_counter.set_on_zero_callback(w.core.free_object)
        if hasattr(w.core, "_on_borrow_released"):
            w.reference_counter.set_borrow_release_callback(w.core._on_borrow_released)
        global_worker = w
        atexit.register(_atexit_shutdown)
        return {
            "node_id": w.core.nodes()[0]["NodeID"] if w.core.nodes() else None,
            "address": getattr(w.core, "address", "local"),
        }


def _atexit_shutdown() -> None:
    try:
        shutdown()
    except Exception:
        logger.debug("atexit shutdown failed", exc_info=True)


def shutdown() -> None:
    global global_worker
    # RC002: detach inside the lock, tear down outside it. core.shutdown()
    # closes RPC clients and parks in run_coro — holding _init_lock across
    # that is the PR-7 livelock shape (any thread entering init/shutdown
    # meanwhile would wedge behind a multi-second teardown). A concurrent
    # init() is serialized by the _teardown_done event instead of the lock.
    with _init_lock:
        w = global_worker
        if w is not None:
            global_worker = None
            _teardown_done.clear()
    if w is None:
        # a concurrent shutdown() may still be mid-teardown: keep this
        # function's completed-on-return contract (atexit relies on it —
        # returning early would let the interpreter die under the other
        # thread's run_coro client sweep)
        _teardown_done.wait(timeout=60)
        return
    try:
        if w.core is not None:
            w.reference_counter.freeze()
            try:
                w.core.shutdown()
            except Exception:
                logger.exception("Error during shutdown")
    finally:
        _teardown_done.set()


def is_initialized() -> bool:
    return global_worker is not None and global_worker.connected


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    from ray_tpu.dag_compiled import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        # compiled-DAG results live in channels, not the object store
        return refs.get(timeout)
    w = _require_connected()
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    if any(isinstance(r, CompiledDAGRef) for r in ref_list):
        if not all(isinstance(r, CompiledDAGRef) for r in ref_list):
            raise TypeError(
                "ray_tpu.get() cannot mix CompiledDAGRefs with ObjectRefs")
        return [r.get(timeout) for r in ref_list]
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get() expects ObjectRef(s), got {type(r)}")
    values = w.core.get(ref_list, timeout=timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    w = _require_connected()
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    return w.core.put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    w = _require_connected()
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns cannot exceed the number of refs")
    return w.core.wait(refs, num_returns, timeout, fetch_local)


def kill(actor_handle, *, no_restart: bool = True) -> None:
    w = _require_connected()
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    w.core.kill_actor(actor_handle._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    w = _require_connected()
    w.core.cancel(ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: Optional[str] = None):
    w = _require_connected()
    from ray_tpu.actor import ActorHandle

    actor_id = w.core.get_actor(name, namespace)
    return ActorHandle._from_actor_id(actor_id)


def nodes() -> List[Dict[str, Any]]:
    return _require_connected().core.nodes()


def cluster_resources() -> Dict[str, float]:
    return _require_connected().core.cluster_resources()


def available_resources() -> Dict[str, float]:
    return _require_connected().core.available_resources()
