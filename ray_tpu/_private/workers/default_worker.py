"""Worker process entry point — executes tasks and hosts actors.

Reference: python/ray/_private/workers/default_worker.py:23 (worker entry)
+ the execution side of src/ray/core_worker/task_execution/ (TaskReceiver
task_receiver.h:43, ordered actor queues, ConcurrencyGroupManager) and the
Cython task_execution_handler (_raylet.pyx:2318).

The worker:
- registers with its raylet, serves PushTask / CreateActor / PushActorTask,
- owns a CoreWorker so user tasks can submit nested tasks / put objects,
- applies lease context (TPU_VISIBLE_CHIPS) before running user code,
- orders actor tasks per caller by sequence number (reference:
  sequential_actor_submit_queue.cc semantics).
"""

from __future__ import annotations

import functools
import inspect
import logging
import os
import threading
import time
import traceback
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import config
from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import RpcClient, get_client
from ray_tpu._private.serialization import deserialize, loads_function, serialize
from ray_tpu.exceptions import RayActorError, RayTaskError
from ray_tpu.observability import dump as obs_dump
from ray_tpu.observability import events as obs_events
from ray_tpu.observability import timeline as obs_timeline
from ray_tpu.observability import tracing as obs_tracing

logger = logging.getLogger("ray_tpu.worker")


def _queue_wait_histogram():
    """Submit→execution-start wait (the scheduling+lease+dispatch part
    of task latency), exposed on the Prometheus scrape next to
    ray_tpu_task_latency_s. Wall-clock across processes — exact on one
    host, NTP-bounded across hosts."""
    from ray_tpu.util.metrics import get_histogram

    return get_histogram(
        "ray_tpu_task_queue_wait_s",
        description="Task submit-to-execution-start wait",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
        tag_keys=("kind",),
    )


def _ray_call_shim(instance, fn, *args, **kwargs):
    return fn(instance, *args, **kwargs)


def _unpack_arg(a: dict) -> Any:
    if a["is_ref"]:
        ref = ObjectRef(ObjectID(a["object_id"]), owner_addr=tuple(a["owner_addr"]) if a["owner_addr"] else None)
        return ("__ref__", ref)
    return ("__val__", a["value"])


class _ActorRunner:
    """Hosts one actor instance.

    Arrival order IS per-caller submission order (the caller's
    _ActorDispatcher sends one enqueue at a time), so the pool's FIFO
    queue preserves ordering with no seqno windows; results are pushed
    back to the owner asynchronously via its ActorTaskDone RPC
    (reference: direct worker→owner reply path of PushTask,
    core_worker.cc:3315).
    """

    _RESULT_CACHE_MAX = 256
    _DELIVERY_ATTEMPTS = 4

    def __init__(self, actor_id: str, instance: Any, max_concurrency: int):
        self.actor_id = actor_id
        self.instance = instance
        # asyncio actors: any `async def` method gives the actor its own
        # event loop; calls overlap without seqno ordering (reference:
        # concurrency_group_manager.cc + fiber.h async actors, whose
        # default max concurrency is high)
        from ray_tpu._private.async_compat import (
            ASYNC_ACTOR_DEFAULT_CONCURRENCY,
            has_async_methods,
        )

        # inspect the CLASS, not the instance: dir+getattr on the instance
        # would execute @property getters during actor init
        self.is_async = has_async_methods(type(instance))
        if self.is_async and max_concurrency <= 1:
            max_concurrency = ASYNC_ACTOR_DEFAULT_CONCURRENCY
        self.max_concurrency = max(1, max_concurrency)
        self.pool = ThreadPoolExecutor(max_workers=self.max_concurrency, thread_name_prefix=f"actor-{actor_id[:8]}")
        self._loop: Optional[Any] = None
        if self.is_async:
            import asyncio

            self._loop = asyncio.new_event_loop()
            t = threading.Thread(
                target=self._loop.run_forever, daemon=True,
                name=f"actor-loop-{actor_id[:8]}",
            )
            t.start()
        self.dead = False
        self.lock = threading.Lock()
        self.inflight: set = set()  # task_id bins accepted but not finished
        # completed results kept until delivery is confirmed (or LRU-evicted)
        # so the caller's QueryActorTaskResult can recover a lost push
        self.results: "OrderedDict[bytes, list]" = OrderedDict()

    def _call_method(self, method_name: str):
        """Build the invoke callable. For asyncio actors EVERY method runs
        on the actor's event loop — coroutines await there (overlapping),
        sync methods execute serialized on the loop thread, preserving the
        actor's single-threaded state guarantee (reference: async actors
        run everything on the loop). Plain actors call on the pool thread."""
        if method_name == "__ray_call__":
            # fn(instance, *args, **kwargs) — arbitrary code against the
            # actor (reference: ray's injected __ray_call__); used by
            # create_collective_group and compiled-DAG exec loops
            method = functools.partial(_ray_call_shim, self.instance)
        else:
            method = getattr(self.instance, method_name)
        if not self.is_async:
            return lambda args, kwargs: method(*args, **kwargs)
        import asyncio

        async def _invoke(args, kwargs):
            if inspect.iscoroutinefunction(method):
                return await method(*args, **kwargs)
            return method(*args, **kwargs)

        def call(args, kwargs):
            fut = asyncio.run_coroutine_threadsafe(_invoke(args, kwargs), self._loop)
            return fut.result()

        return call

    def submit(self, payload: dict) -> bool:
        """Accept-or-refuse atomically: a task that passes the dead
        gate is in ``inflight`` before the gate can flip, so DrainActor
        either waits for it or the caller re-resolves — never neither."""
        with self.lock:
            if self.dead:
                return False
            self.inflight.add(payload["task_id"])
        try:
            self.pool.submit(self._run, payload)
        except RuntimeError:  # pool shut down by a concurrent hard kill
            with self.lock:
                self.inflight.discard(payload["task_id"])
            return False
        return True

    def submit_batch(self, payloads: List[dict]) -> bool:
        """Atomic batched accept (see submit): the dead gate is checked
        once for the whole batch under the lock."""
        with self.lock:
            if self.dead:
                return False
            for p in payloads:
                self.inflight.add(p["task_id"])
        try:
            for p in payloads:
                self.pool.submit(self._run, p)
        except RuntimeError:
            with self.lock:
                for p in payloads:
                    self.inflight.discard(p["task_id"])
            return False
        return True

    def query(self, task_id_bin: bytes) -> dict:
        with self.lock:
            if task_id_bin in self.results:
                result = self.results.pop(task_id_bin)
                return {
                    "status": "done",
                    "returns": result["returns"],
                    "streaming_done": result.get("streaming_done"),
                    "stream_error": result.get("stream_error"),
                    "failed": bool(result.get("retriable_error")
                                   or result.get("stream_error")),
                }
            if task_id_bin in self.inflight:
                return {"status": "running"}
        return {"status": "unknown"}

    def _run(self, payload: dict) -> None:
        if payload.get("streaming"):
            result = _execute_streaming(
                getattr(self.instance, payload["method_name"]),
                payload["args"],
                payload["kwargs"],
                TaskID(payload["task_id"]),
                payload["method_name"],
                tuple(payload["caller_addr"]),
                actor_id=ActorID.from_hex(payload["actor_id"]),
                trace_ctx=payload.get("trace_ctx"),
                submit_ts=payload.get("submit_ts", 0.0),
            )
        else:
            result = _execute_callable(
                self._call_method(payload["method_name"]),
                payload["args"],
                payload["kwargs"],
                payload["num_returns"],
                TaskID(payload["task_id"]),
                payload["method_name"],
                actor_id=ActorID.from_hex(payload["actor_id"]),
                caller_addr=tuple(payload["caller_addr"]),
                trace_ctx=payload.get("trace_ctx"),
                submit_ts=payload.get("submit_ts", 0.0),
            )
        task_bin = payload["task_id"]
        with self.lock:
            self.inflight.discard(task_bin)
            self.results[task_bin] = result
            while len(self.results) > self._RESULT_CACHE_MAX:
                self.results.popitem(last=False)
        # hand the push to the shared deliverer: the execution thread must
        # NOT block on a result round-trip (a 1-thread actor would
        # serialize every call behind its predecessor's delivery), and
        # batching pushes per caller costs one RPC per batch, not per task
        _deliverer().deliver(self, tuple(payload["caller_addr"]), task_bin, {
            "task_id_bin": task_bin,
            "returns": result["returns"],
            "dropped_borrows": result.get("dropped_borrows") or [],
            # streaming methods: the done RPC is the reliable finalizer
            # in case the StreamingDone push was lost
            "streaming_done": result.get("streaming_done"),
            "stream_error": result.get("stream_error"),
            "failed": bool(result.get("retriable_error")
                           or result.get("stream_error")),
        })


class _ResultDeliverer:
    """Asynchronous, batched ActorTasksDone delivery (reference: the
    direct worker→owner reply path of PushTask, core_worker.cc:3315 —
    replies ride the io_context, never an execution thread).

    Execution threads enqueue results; one drain task per caller on the
    worker's io loop sends them in batches. On delivery failure after
    retries the result stays in the runner's cache for the caller's
    requery to collect."""

    _MAX_BATCH = 64
    _DELIVERY_ATTEMPTS = 4

    def __init__(self, loop_thread):
        self._loop = loop_thread.loop
        self._queues: Dict[Tuple[str, int], list] = {}
        self._draining: set = set()

    def deliver(self, runner: "_ActorRunner", caller_addr: Tuple[str, int],
                task_bin: bytes, result_kwargs: dict) -> None:
        import asyncio

        def _enqueue():
            self._queues.setdefault(caller_addr, []).append(
                (runner, task_bin, result_kwargs))
            if caller_addr not in self._draining:
                self._draining.add(caller_addr)
                asyncio.ensure_future(self._drain(caller_addr))

        self._loop.call_soon_threadsafe(_enqueue)

    async def _drain(self, addr: Tuple[str, int]) -> None:
        try:
            while True:
                q = self._queues.get(addr)
                if not q:
                    return  # no await between this check and finally:
                    # a racing _enqueue can't slip past the discard
                batch = q[: self._MAX_BATCH]
                del q[: self._MAX_BATCH]
                await self._send(addr, batch)
        finally:
            self._draining.discard(addr)

    async def _send(self, addr: Tuple[str, int], batch: list) -> None:
        import asyncio

        delay = 0.5
        for attempt in range(self._DELIVERY_ATTEMPTS):
            try:
                await get_client(addr).acall(
                    "ActorTasksDone",
                    results=[kw for _, _, kw in batch], timeout=30)
            except Exception as e:  # noqa: BLE001
                if attempt == self._DELIVERY_ATTEMPTS - 1:
                    # leave results cached; the caller's requery will
                    # collect them if the caller is still alive
                    logger.warning(
                        "could not deliver %d actor task result(s) to "
                        "%s: %s", len(batch), addr, e)
                    return
                await asyncio.sleep(delay)
                delay *= 2
            else:
                for runner, task_bin, _ in batch:
                    with runner.lock:
                        runner.results.pop(task_bin, None)
                return


_DELIVERER: Optional[_ResultDeliverer] = None
_DELIVERER_LOCK = threading.Lock()


def _deliverer() -> _ResultDeliverer:
    with _DELIVERER_LOCK:
        global _DELIVERER
        if _DELIVERER is None:
            _DELIVERER = _ResultDeliverer(
                worker_mod.global_worker.core.loop_thread)
        return _DELIVERER


def _resolve_args(packed_args: List[dict], packed_kwargs: Dict[str, dict]) -> Tuple[tuple, dict]:
    w = worker_mod.global_worker
    args = []
    for a in packed_args:
        kind, v = _unpack_arg(a)
        if kind == "__ref__":
            args.append(w.core.get([v])[0])
        else:
            args.append(deserialize(v))
    kwargs = {}
    for k, a in packed_kwargs.items():
        kind, v = _unpack_arg(a)
        kwargs[k] = w.core.get([v])[0] if kind == "__ref__" else deserialize(v)
    return tuple(args), kwargs


def _execute_callable(
    fn,
    packed_args: List[dict],
    packed_kwargs: Dict[str, dict],
    num_returns: int,
    task_id: TaskID,
    name: str,
    actor_id: Optional[ActorID] = None,
    caller_addr: Optional[Tuple[str, int]] = None,
    trace_ctx=None,
    submit_ts: float = 0.0,
) -> dict:
    """Run user code; package returns (inline small / shared-memory big).

    The propagated trace context is activated for the WHOLE body — not
    just the user-code span — so the worker-side bus gates record the
    RUNNING transition and result-packaging object events too."""
    with obs_tracing.activated(trace_ctx):
        return _execute_callable_body(
            fn, packed_args, packed_kwargs, num_returns, task_id, name,
            actor_id, caller_addr, submit_ts)


def _execute_callable_body(
    fn,
    packed_args: List[dict],
    packed_kwargs: Dict[str, dict],
    num_returns: int,
    task_id: TaskID,
    name: str,
    actor_id: Optional[ActorID],
    caller_addr: Optional[Tuple[str, int]],
    submit_ts: float,
) -> dict:
    from ray_tpu._private.serialization import collect_object_refs

    kind = "actor_task" if actor_id else "task"
    w = worker_mod.global_worker
    w.set_task_context(task_id, actor_id)
    # execution start: gives the timeline its queued-vs-running split
    # (reference: task_event_buffer.h RUNNING state transition)
    try:
        w.core._record_task_event(task_id, name, "RUNNING", kind=kind)
        if submit_ts:
            _queue_wait_histogram().observe(
                max(0.0, time.time() - submit_ts), tags={"kind": kind})
    except Exception:  # noqa: BLE001
        pass
    all_borrows: List[tuple] = []  # every AddBorrower sent for this task
    try:
        args, kwargs = _resolve_args(packed_args, packed_kwargs)
        # the active (propagated) context makes this execution a child
        # span of the caller's active span (cross-process parenting);
        # untraced tasks fall straight through
        with obs_tracing.span(
                name, kind=kind, attrs={"task_id": task_id.hex()}):
            result = fn(args, kwargs)
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(f"expected {num_returns} return values, got {len(values)}")
        from ray_tpu._private.serialization import serialize_prepare

        returns = []
        for i, v in enumerate(values):
            with collect_object_refs() as col:
                sv = serialize_prepare(v)
            # refs nested in the return value: register the CALLER as
            # borrower with each owner BEFORE replying, while our own
            # refs still pin the objects (reference_counter.h:44 —
            # borrower handoff on task return). The registered handoffs
            # ride back in the reply ("borrows") so the caller can
            # deregister any it never claims by deserializing (advisor
            # finding, round 1: unclaimed handoffs pinned forever).
            borrows = []
            if col.refs and caller_addr is not None:
                for r in col.refs:
                    owner = r.owner_address or w.core.address
                    if tuple(owner) == tuple(caller_addr):
                        continue  # caller owns it already
                    try:
                        rep = get_client(tuple(owner)).call(
                            "AddBorrower",
                            object_id_bin=r.id().binary(),
                            borrower=tuple(caller_addr),
                            timeout=10,
                        )
                        entry = (
                            r.id().binary(), tuple(owner),
                            (rep or {}).get("epoch") or 0,
                        )
                        borrows.append(entry)
                        all_borrows.append(entry)
                    except Exception:
                        pass
            try:
                if sv.total <= config.object_store_inline_max_bytes:
                    returns.append({"kind": "inline",
                                    "data": sv.to_bytes(copy_path="inline"),
                                    "borrows": borrows})
                else:
                    oid = ObjectID.from_index(task_id, i + 1)
                    # big returns go straight into the reserved mapping
                    # (Create → write-in-place → Seal): 0 payload copies
                    w.core._plasma_put_segments(oid, sv)
                    # big returns bypass put_serialized, so the bus event is
                    # recorded here (executor-side, gated on the activated
                    # trace context like every worker event)
                    if obs_tracing.active():
                        obs_events.record_event(
                            "object_put", size=sv.total,
                            job_id=w.core.job_id.hex(), inline=False)
                    returns.append(
                        {"kind": "plasma", "node_id": w.core.node_id,
                         "borrows": borrows}
                    )
            finally:
                sv.release()
        return {"returns": returns}
    except BaseException as e:  # noqa: BLE001
        tb = traceback.format_exc()
        err = RayTaskError(name, tb, e if isinstance(e, Exception) else None)
        data = serialize(err)
        return {
            "returns": [{"kind": "inline", "data": data} for _ in range(num_returns)],
            "retriable_error": True,
            # borrows registered before the failure (e.g. value 0 packaged,
            # value 1 raised): report them so the caller's ledger can
            # deregister — the error reply drops the values they rode in on
            "dropped_borrows": all_borrows,
        }
    finally:
        w.set_task_context(None, None)


def _execute_streaming(
    fn,
    packed_args: List[dict],
    packed_kwargs: Dict[str, dict],
    task_id: TaskID,
    name: str,
    caller_addr: Tuple[str, int],
    actor_id: Optional[ActorID] = None,
    trace_ctx=None,
    submit_ts: float = 0.0,
) -> dict:
    """Run a generator task, pushing one StreamingYield per value to the
    caller as it is produced (reference: task_manager.cc:778 generator
    item returns). The per-yield ack is the backpressure: the generator
    does not advance until the caller has registered the previous item."""
    w = worker_mod.global_worker
    w.set_task_context(task_id, actor_id)
    if submit_ts:
        try:
            _queue_wait_histogram().observe(
                max(0.0, time.time() - submit_ts),
                tags={"kind": "actor_task" if actor_id else "task"})
        except Exception:  # noqa: BLE001
            pass
    client = get_client(tuple(caller_addr))
    idx = 0
    try:
        args, kwargs = _resolve_args(packed_args, packed_kwargs)
        with obs_tracing.inbound_span(
                trace_ctx, name=name,
                kind="actor_task" if actor_id else "task",
                attrs={"task_id": task_id.hex(), "streaming": True}):
            from ray_tpu._private.serialization import serialize_prepare

            for value in fn(*args, **kwargs):
                sv = serialize_prepare(value)
                try:
                    if sv.total <= config.object_store_inline_max_bytes:
                        rep = client.call(
                            "StreamingYield", task_id_bin=task_id.binary(),
                            index=idx, kind="inline",
                            data=sv.to_bytes(copy_path="inline"),
                            timeout=60,
                        )
                    else:
                        oid = ObjectID.from_index(task_id, idx + 1)
                        w.core._plasma_put_segments(oid, sv)
                        if obs_tracing.active():
                            obs_events.record_event(
                                "object_put", size=sv.total,
                                job_id=w.core.job_id.hex(), inline=False)
                        rep = client.call(
                            "StreamingYield", task_id_bin=task_id.binary(),
                            index=idx, kind="plasma", node_id=w.core.node_id,
                            timeout=60,
                        )
                finally:
                    sv.release()
                if not (rep or {}).get("ok", True):
                    break  # consumer abandoned the stream — stop producing
                idx += 1
                # consumer backpressure: pause while the un-consumed buffer
                # on the caller is deep (reference: generator_backpressure_
                # num_objects); the registration ack alone doesn't bound it
                limit = config.streaming_generator_buffer_size
                while (rep or {}).get("pending", 0) >= limit:
                    time.sleep(0.02)
                    try:
                        rep = client.call(
                            "StreamingCredit", task_id_bin=task_id.binary(),
                            timeout=30,
                        )
                    except Exception:  # noqa: BLE001
                        break
                    if not rep.get("ok", True):
                        rep = {"ok": False}
                        break
                if not (rep or {}).get("ok", True):
                    break
        done = {"count": idx, "error": None}
    except BaseException as e:  # noqa: BLE001
        tb = traceback.format_exc()
        err = RayTaskError(name, tb, e if isinstance(e, Exception) else None)
        done = {"count": idx, "error": serialize(err)}
    finally:
        w.set_task_context(None, None)
    try:
        client.call(
            "StreamingDone", task_id_bin=task_id.binary(),
            count=done["count"], error=done["error"], timeout=60,
        )
    except Exception:  # noqa: BLE001 — the reply carries the same info
        pass
    reply = {"returns": [], "streaming_done": done["count"]}
    if done["error"] is not None:
        reply["stream_error"] = done["error"]
    return reply


class WorkerServer:
    def __init__(self, core: CoreWorker, raylet_addr: Tuple[str, int], worker_id: str):
        self.core = core
        self.worker_id = worker_id
        self.raylet_addr = raylet_addr
        self.actors: Dict[str, _ActorRunner] = {}
        self._task_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="exec")
        from collections import OrderedDict

        # bytes -> fn, LRU-bounded: each entry pins the full cloudpickle
        # byte string as its key, so an unbounded dict would grow with
        # every distinct closure this worker ever ran
        self._function_cache: Any = OrderedDict()
        self._fn_by_key: Any = OrderedDict()  # content hash -> fn (LRU)
        # task_id bin -> executing thread ident, for CancelTask; the lock
        # makes register/raise/unregister mutually exclusive so a cancel
        # cannot target a thread that already moved on to another task
        self._running_tasks: Dict[bytes, int] = {}
        self._cancel_lock = threading.Lock()
        # node drain recall: once set, task pushes are refused with a
        # node_draining reply — the caller returns the warm lease and
        # re-leases elsewhere for free, so a sustained task stream
        # doesn't pin its lease to the dying node for the full deadline
        self._node_draining = False
        core.server.register("NotifyNodeDraining", self.NotifyNodeDraining,
                             inline=True)
        core.server.register("PushTask", self.PushTask)
        core.server.register("PushTaskBatch", self.PushTaskBatch)
        core.server.register("CancelTask", self.CancelTask)
        core.server.register("CreateActor", self.CreateActor)
        # enqueue-and-ack handlers only append to the runner's pool queue:
        # inline (no executor handoff) — the ack is on the wire the same
        # loop tick the push frame decodes
        # single-item fallback of PushActorTasks — raycheck: disable=RC003
        core.server.register("PushActorTask", self.PushActorTask,
                             inline=True)
        core.server.register("PushActorTasks", self.PushActorTasks,
                             inline=True)
        core.server.register("QueryActorTaskResult",
                             self.QueryActorTaskResult, inline=True)
        core.server.register("KillActor", self.KillActor)
        core.server.register("DrainActor", self.DrainActor)
        core.server.register("SetLeaseContext", self.SetLeaseContext)
        # operator/debug endpoint: ask a worker to exit gracefully out of
        # band (the raylet path signals instead) — raycheck: disable=RC003
        core.server.register("Exit", self.Exit)

    # -- lease context: assign TPU chips before user code runs ----------
    def SetLeaseContext(self, lease_id: str, tpu_chips: List[int], resources: Dict[str, float]) -> dict:
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        if tpu_chips:
            TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
                [str(c) for c in tpu_chips]
            )
            os.environ["JAX_PLATFORMS"] = ""  # let jax pick up the TPU
        w = worker_mod.global_worker
        w.assigned_resources = dict(resources)
        w.assigned_resources["tpu_chips"] = list(tpu_chips)
        w.current_lease_id = lease_id
        return {"ok": True}

    @staticmethod
    def _apply_py_paths(paths) -> None:
        import sys

        for p in paths or []:
            if p not in sys.path:
                sys.path.append(p)

    def _apply_runtime_env(self, env) -> None:
        """Apply a prepared runtime env (env_vars / working_dir /
        py_modules packages) — idempotent per env hash; marks this
        worker like the reference's env-dedicated workers."""
        if not env:
            return
        import tempfile

        from ray_tpu._private import runtime_env as rt

        cache = os.path.join(tempfile.gettempdir(), "ray_tpu_rtenv")
        os.makedirs(cache, exist_ok=True)
        try:
            rt.apply_runtime_env(env, self.core.gcs, cache)
        except Exception:  # noqa: BLE001
            logger.exception("runtime_env application failed")
            raise

    # -- normal tasks ---------------------------------------------------
    _FN_KEY_CACHE_MAX = 512
    _FN_BYTES_CACHE_MAX = 64

    def _resolve_function(self, spec_payload: dict):
        """Function bytes ship once per worker: later pushes carry only
        ``function_key`` (content hash of the cloudpickle bytes) and hit
        the key cache (reference: the function table exported through
        the GCS once per job, _private/function_manager.py). Returns
        (fn, None) or (None, error_reply)."""
        key = spec_payload.get("function_key")
        fn_bytes = spec_payload.get("serialized_function")
        if fn_bytes is None:
            fn = self._fn_by_key.get(key)
            if fn is None:
                # evicted (or a restarted worker the driver mistook for
                # warm): ask for the bytes instead of failing the task
                return None, {"need_function": True}
            self._fn_by_key.move_to_end(key)
            return fn, None
        fn = self._function_cache.get(fn_bytes)
        if fn is not None:
            self._function_cache.move_to_end(fn_bytes)
        else:
            try:
                fn = loads_function(fn_bytes)
            except BaseException as e:  # noqa: BLE001
                err = serialize(
                    RayTaskError(
                        spec_payload["function_name"],
                        f"Failed to deserialize the remote function: "
                        f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
                    )
                )
                if spec_payload.get("streaming"):
                    # streams have no return slots: surface via stream error
                    return None, {"returns": [], "streaming_done": 0,
                                  "stream_error": err}
                return None, {
                    "returns": [
                        {"kind": "inline", "data": err}
                        for _ in range(spec_payload["num_returns"])
                    ]
                }
            self._function_cache[fn_bytes] = fn
            while len(self._function_cache) > self._FN_BYTES_CACHE_MAX:
                self._function_cache.popitem(last=False)
        if key:
            self._fn_by_key[key] = fn
            self._fn_by_key.move_to_end(key)
            while len(self._fn_by_key) > self._FN_KEY_CACHE_MAX:
                self._fn_by_key.popitem(last=False)
        return fn, None

    def NotifyNodeDraining(self) -> dict:
        self._node_draining = True
        return {"ok": True}

    def PushTask(self, spec_payload: dict) -> dict:
        if self._node_draining and not spec_payload.get("drain_final"):
            # drain_final marks work that was leased HERE before the
            # drain and cannot run anywhere else — the drain deadline
            # exists so exactly this work can finish; refuse the rest
            return {"node_draining": True}
        self._apply_py_paths(spec_payload.get("py_paths"))
        self._apply_runtime_env(spec_payload.get("runtime_env"))
        fn, err_reply = self._resolve_function(spec_payload)
        if err_reply is not None:
            return err_reply
        caller_addr = spec_payload.get("caller_addr")
        if spec_payload.get("streaming"):
            fut = self._task_pool.submit(
                _execute_streaming,
                fn,
                spec_payload["args"],
                spec_payload["kwargs"],
                TaskID(spec_payload["task_id"]),
                spec_payload["function_name"],
                tuple(caller_addr),
                trace_ctx=spec_payload.get("trace_ctx"),
                submit_ts=spec_payload.get("submit_ts", 0.0),
            )
            return fut.result()
        task_bin = spec_payload["task_id"]

        def _runner():
            with self._cancel_lock:
                self._running_tasks[task_bin] = threading.get_ident()
            task_hex = bytes(task_bin).hex() if obs_timeline.enabled() \
                else ""
            if task_hex:
                obs_timeline.mark_task(task_hex, "run_start")
            try:
                return _execute_callable(
                    lambda args, kwargs: fn(*args, **kwargs),
                    spec_payload["args"],
                    spec_payload["kwargs"],
                    spec_payload["num_returns"],
                    TaskID(task_bin),
                    spec_payload["function_name"],
                    None,
                    tuple(caller_addr) if caller_addr else None,
                    trace_ctx=spec_payload.get("trace_ctx"),
                    submit_ts=spec_payload.get("submit_ts", 0.0),
                )
            finally:
                if task_hex:
                    obs_timeline.mark_task(task_hex, "run_end")
                with self._cancel_lock:
                    self._running_tasks.pop(task_bin, None)

        return self._task_pool.submit(_runner).result()

    def PushTaskBatch(self, spec_payloads: list) -> dict:
        """Execute a batch of queued same-class tasks serially in one
        RPC roundtrip (reference: the raylet's lease reuse amortizes
        scheduling, but each reference task still pays one PushTask RPC
        — batching amortizes the roundtrip too, which dominates for
        small tasks).

        Each task's reply is pushed to the caller the moment it
        finishes (oneway ``NormalTaskDone``) so an early result is
        visible to ``ray.wait`` while later batch members still run;
        the positional ``replies`` in the final return are the reliable
        fallback for a lost push — the caller claims each (task,
        attempt) exactly once."""
        if self._node_draining and \
                not all(p.get("drain_final") for p in spec_payloads):
            return {"node_draining": True}
        replies = []
        for p in spec_payloads:
            r = self.PushTask(p)
            replies.append(r)
            addr = p.get("caller_addr")
            if addr and not r.get("need_function") \
                    and not r.get("node_draining"):
                try:
                    get_client(tuple(addr)).call_oneway(
                        "NormalTaskDone",
                        task_id_bin=p["task_id"],
                        attempt_number=p.get("attempt_number", 0),
                        reply=r,
                    )
                except Exception:  # noqa: BLE001 — fallback is the reply
                    pass
        return {"replies": replies}

    def CancelTask(self, task_id_bin: bytes, force: bool = False) -> dict:
        """Interrupt a RUNNING task (reference: CoreWorker::HandleCancelTask,
        core_worker.cc CancelTask). Non-force raises TaskCancelledError in
        the executing thread at its next bytecode boundary; force kills the
        worker process.

        The register/raise/unregister critical sections share _cancel_lock,
        so the raise only targets a thread still registered for THIS task.
        (As in the reference's Python-level cancel, delivery is
        asynchronous: a task finishing in the same instant can see the
        exception surface in its packaging code — the caller discards that
        reply since its returns are already poisoned.)"""
        from ray_tpu.exceptions import TaskCancelledError

        if force:
            threading.Timer(0.05, lambda: os._exit(1)).start()
            return {"ok": True, "forced": True}
        import ctypes

        with self._cancel_lock:
            ident = self._running_tasks.get(bytes(task_id_bin))
            if ident is None:
                return {"ok": False, "running": False}
            n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError)
            )
            if n > 1:  # hit more than one thread: undo
                ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(ident), None)
        return {"ok": n == 1}

    # -- actors ---------------------------------------------------------
    def CreateActor(self, actor_id: str, serialized_spec: bytes) -> dict:
        import pickle

        if obs_timeline.enabled():
            # marked at CreateActor ARRIVAL, not backdated to fork: a
            # prestarted/pooled worker's spawn predates the actor's
            # whole lifecycle and would scramble the phase order.
            # spawn_age_s distinguishes the two offline — near-zero
            # means this lease paid for a cold fork+boot.
            spawned = os.environ.get("RAY_TPU_WORKER_SPAWNED_MONO")
            obs_timeline.mark_actor(
                actor_id, "worker_started",
                spawn_age_s=round(time.monotonic() - float(spawned), 3)
                if spawned else None)
        spec = pickle.loads(serialized_spec)
        self._apply_py_paths(spec.get("py_paths"))
        try:
            self._apply_runtime_env(spec.get("runtime_env"))
            cls = loads_function(spec["serialized_class"])
            args, kwargs = _resolve_args(spec["args"], spec["kwargs"])
            instance = cls(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}\n{traceback.format_exc()}"}
        obs_timeline.mark_actor(actor_id, "init_done")
        self.actors[actor_id] = _ActorRunner(actor_id, instance, spec.get("max_concurrency", 1))
        return {"ok": True}

    def PushActorTask(self, payload: dict) -> dict:
        """Enqueue-and-ack: execution result goes back via ActorTaskDone."""
        runner = self.actors.get(payload["actor_id"])
        if runner is None or not runner.submit(payload):
            return {"accepted": False}
        return {"accepted": True}

    def PushActorTasks(self, payloads: List[dict]) -> dict:
        """Batched enqueue-and-ack (one RPC per caller batch): payloads
        enqueue in list order, preserving per-caller submission order.
        All-or-nothing: if the batch races the drain gate, nothing is
        enqueued and the caller re-resolves the whole batch — a partial
        accept would double-run the accepted prefix elsewhere."""
        if not payloads:
            return {"accepted": True}
        runner = self.actors.get(payloads[0]["actor_id"])
        if runner is None or not runner.submit_batch(payloads):
            return {"accepted": False}
        return {"accepted": True}

    def QueryActorTaskResult(self, actor_id: str, task_id_bin: bytes) -> dict:
        """Recovery path for a lost ActorTaskDone push."""
        runner = self.actors.get(actor_id)
        if runner is None:
            return {"status": "unknown"}
        return runner.query(task_id_bin)

    def DrainActor(self, actor_id: str, timeout_s: float = 30.0) -> dict:
        """Graceful actor handoff for a draining node: stop accepting
        new tasks (PushActorTasks answers accepted=False, so callers
        re-resolve to the restarted incarnation) and wait for every
        ACCEPTED task to finish — their results are still delivered /
        queryable, so a drain loses no in-flight actor call. The GCS
        restarts the actor elsewhere only after this returns."""
        runner = self.actors.get(actor_id)
        if runner is None:
            return {"ok": True, "absent": True}
        with runner.lock:  # atomic with submit's accept (see submit)
            runner.dead = True  # gates acceptance only; the pool keeps running
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            with runner.lock:
                if not runner.inflight:
                    break
            time.sleep(0.02)
        with runner.lock:
            leftover = len(runner.inflight)
        return {"ok": True, "drained": leftover == 0, "inflight": leftover}

    def KillActor(self, actor_id: str) -> dict:
        runner = self.actors.get(actor_id)
        if runner is not None:
            with runner.lock:
                runner.dead = True
            runner.pool.shutdown(wait=False, cancel_futures=True)
            # keep the runner REGISTERED: its results cache must stay
            # queryable while ActorTaskDone pushes are still in flight.
            # Popping it here turned a racing lost delivery into an
            # authoritative-looking "unknown" from a live worker — the
            # caller then failed a task whose result actually existed
            # (flaked test_actor_restarts_elsewhere_on_drain). The
            # process exit below is what frees everything.
            # a dedicated-actor worker exits so its resources free up
            if all(r.dead for r in self.actors.values()):
                threading.Timer(0.5, lambda: os._exit(0)).start()
        return {"ok": True}

    def Exit(self) -> dict:
        threading.Timer(0.1, lambda: os._exit(0)).start()
        return {"ok": True}


def main() -> None:
    logging.basicConfig(level="INFO", format="[worker] %(levelname)s %(message)s")
    # honor JAX_PLATFORMS via jax.config: environment-level platform
    # pinning can be overridden by site hooks that call
    # jax.config.update("jax_platforms", ...) at interpreter start
    # (e.g. a tunneled-TPU plugin forcing itself first) — a worker
    # told to run CPU must NEVER lazily initialize a remote TPU
    # backend mid-task (observed: CreateActor unpickling a jax array
    # hung on the tunnel). config.update after import wins.
    jp = os.environ.get("JAX_PLATFORMS")
    if jp:
        try:
            import jax

            jax.config.update("jax_platforms", jp)
        except Exception:  # noqa: BLE001 — jax absent or config gone
            pass
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    raylet_host, raylet_port = os.environ["RAY_TPU_RAYLET_ADDR"].rsplit(":", 1)
    gcs_host, gcs_port = os.environ["RAY_TPU_GCS_ADDR"].rsplit(":", 1)
    store_socket = os.environ["RAY_TPU_STORE_SOCKET"]
    node_id = os.environ["RAY_TPU_NODE_ID"]
    config.from_json(os.environ.get("RAY_TPU_CONFIG_JSON", "{}"))

    w = worker_mod.Worker()
    w.mode = worker_mod.WORKER_MODE
    worker_mod.global_worker = w

    core = CoreWorker(
        gcs_addr=(gcs_host, int(gcs_port)),
        raylet_addr=(raylet_host, int(raylet_port)),
        store_socket=store_socket,
        node_id=node_id,
        job_id=JobID.from_int(0),
        is_driver=False,
        worker_id_hex=worker_id,
    )
    w.core = core
    w.reference_counter.set_on_zero_callback(core.free_object)
    WorkerServer(core, (raylet_host, int(raylet_port)), worker_id)

    # process-lifetime client: the raylet owns this process and the
    # block-forever wait below never falls through —
    # raycheck: disable=RC006
    raylet = RpcClient(raylet_host, int(raylet_port), core.loop_thread)
    reply = raylet.call_retrying("RegisterWorker", worker_id=worker_id, addr=core.address)
    if not reply.get("ok"):
        logger.error("raylet rejected registration")
        raylet.close()
        return
    logger.info("worker %s serving at %s", worker_id[:8], core.address)

    # block forever; raylet owns our lifetime
    threading.Event().wait()


if __name__ == "__main__":
    main()
