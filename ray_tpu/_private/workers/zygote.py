"""Prefork worker factory — the "zygote" process.

The reference hides Python worker startup latency by prestarting idle
workers (src/ray/raylet/worker_pool.cc PrestartWorkers) — but each
prestart is still a cold interpreter plus the full import chain, and a
TPU host's CPU cores are scarce next to its chips: spawning 50 actors
costs 50 × (interpreter + imports) of the one core the control plane
lives on. The zygote pays the import ONCE, then every worker is a
``fork()`` — milliseconds, with the imported pages shared copy-on-write
across the whole worker pool.

Protocol (newline-delimited JSON over stdin/stdout):

    raylet -> zygote: {"env": {...}, "log_path": "..."}   spawn request
    zygote -> raylet: {"pid": N} | {"error": "..."}
    raylet -> zygote: {"op": "ping"} -> {"ok": true}

The zygote is single-threaded and opens no sockets, so fork is safe: no
locks can be held, no event loop state is duplicated. Children join the
raylet's process group (nothing calls setsid), so group-level teardown
behaves exactly like subprocess-spawned workers. Exited children are
reaped on every protocol message and on a 5 s idle tick.
"""

from __future__ import annotations

import json
import os
import select
import sys


def _reap() -> None:
    while True:
        try:
            pid, _ = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return


def _child(req: dict, protocol_fds) -> None:
    """Become the worker. Never returns."""
    try:
        for fd in protocol_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        log_path = req.get("log_path")
        if log_path:
            logfd = os.open(log_path,
                            os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            os.dup2(logfd, 1)
            os.dup2(logfd, 2)
            os.close(logfd)
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)
        os.close(devnull)
        os.environ.update(req.get("env", {}))
        try:
            # forked children keep the zygote's /proc cmdline; at least
            # stamp the kernel comm (ps -o comm) for diagnosability
            import ctypes

            wid = req.get("env", {}).get("RAY_TPU_WORKER_ID", "")[:7]
            libc = ctypes.CDLL(None, use_errno=True)
            libc.prctl(15, ctypes.c_char_p(f"rtw:{wid}".encode()), 0, 0, 0)
        except Exception:  # noqa: BLE001
            pass
        from ray_tpu._private.workers import default_worker

        default_worker.main()
    except BaseException:  # noqa: BLE001 — a child must never fall back
        import traceback

        traceback.print_exc()
    finally:
        os._exit(1)


def main() -> None:
    # the heavy imports happen ONCE, before the serve loop; every spawn
    # is then a fork of this warmed image. jax is included (import only
    # — no backend init, no threads): actor workers almost always need
    # it, and one warmed copy is shared copy-on-write pool-wide.
    import ray_tpu._private.workers.default_worker  # noqa: F401

    try:
        import jax  # noqa: F401
    except ImportError:
        pass

    inp = sys.stdin.buffer
    out = sys.stdout.buffer
    protocol_fds = (inp.fileno(), out.fileno())
    while True:
        ready, _, _ = select.select([inp], [], [], 5.0)
        _reap()
        if not ready:
            continue
        line = inp.readline()
        if not line:
            return  # raylet closed the pipe; running workers unaffected
        try:
            req = json.loads(line)
        except ValueError:
            continue
        if req.get("op") == "ping":
            out.write(json.dumps({"ok": True}).encode() + b"\n")
            out.flush()
            continue
        try:
            pid = os.fork()
        except OSError as e:
            out.write(json.dumps({"error": str(e)}).encode() + b"\n")
            out.flush()
            continue
        if pid == 0:
            _child(req, protocol_fds)  # never returns
        out.write(json.dumps({"pid": pid}).encode() + b"\n")
        out.flush()


if __name__ == "__main__":
    main()
