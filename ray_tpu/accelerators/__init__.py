from ray_tpu.accelerators.accelerator import AcceleratorManager
from ray_tpu.accelerators.tpu import TPUAcceleratorManager

_MANAGERS = {
    "TPU": TPUAcceleratorManager,
}


def get_accelerator_manager(resource_name: str):
    return _MANAGERS.get(resource_name)


def get_all_accelerator_managers():
    return dict(_MANAGERS)
