from ray_tpu.accelerators.accelerator import AcceleratorManager
from ray_tpu.accelerators.fake_chip import FakeChipAcceleratorManager
from ray_tpu.accelerators.tpu import TPUAcceleratorManager

_MANAGERS = {
    "TPU": TPUAcceleratorManager,
    # proof-of-ABC backend, active only under RAY_TPU_FAKE_CHIP_COUNT
    "FakeChip": FakeChipAcceleratorManager,
}


def get_accelerator_manager(resource_name: str):
    return _MANAGERS.get(resource_name)


def get_all_accelerator_managers():
    return dict(_MANAGERS)
