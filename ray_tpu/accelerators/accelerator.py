"""Accelerator plugin interface.

Reference: python/ray/_private/accelerators/accelerator.py (AcceleratorManager
ABC). TPU is the first-class implementation here; the interface stays open
for others (the reference ships nvidia/amd/neuron/hpu/npu backends).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional


class AcceleratorManager(ABC):
    @staticmethod
    @abstractmethod
    def get_resource_name() -> str: ...

    @staticmethod
    @abstractmethod
    def get_visible_accelerator_ids_env_var() -> str: ...

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int: ...

    @staticmethod
    @abstractmethod
    def get_current_node_accelerator_type() -> Optional[str]: ...

    @staticmethod
    @abstractmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]: ...

    @staticmethod
    @abstractmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None: ...

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple:
        return (True, "")

    @staticmethod
    def get_current_node_additional_resources() -> dict:
        return {}
