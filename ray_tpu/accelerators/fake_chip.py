"""FakeChip — a second accelerator backend that exists to prove the
plugin ABC (reference: python/ray/_private/accelerators/ ships eight
backends; an interface with one implementation is untested by
construction).

Activated by ``RAY_TPU_FAKE_CHIP_COUNT=N`` — node resource detection
then reports N ``FakeChip`` units through exactly the same
AcceleratorManager surface TPU uses, and tests schedule against them
without any hardware. Also the model for adding a real second backend:
implement the ABC, add one line to ``accelerators._MANAGERS``.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ray_tpu.accelerators.accelerator import AcceleratorManager

FAKE_CHIP_RESOURCE = "FakeChip"
FAKE_CHIP_COUNT_ENV = "RAY_TPU_FAKE_CHIP_COUNT"
FAKE_CHIP_VISIBLE_ENV = "FAKECHIP_VISIBLE_IDS"


class FakeChipAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return FAKE_CHIP_RESOURCE

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return FAKE_CHIP_VISIBLE_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        try:
            return int(os.environ.get(FAKE_CHIP_COUNT_ENV, "0"))
        except ValueError:
            return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        return "FAKE-CHIP-V1" if \
            FakeChipAcceleratorManager.get_current_node_num_accelerators() \
            else None

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        v = os.environ.get(FAKE_CHIP_VISIBLE_ENV)
        return v.split(",") if v else None

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        os.environ[FAKE_CHIP_VISIBLE_ENV] = ",".join(str(i) for i in ids)

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple:
        if quantity != int(quantity):
            return (False, "FakeChip must be requested in whole units")
        return (True, "")
