"""TPU accelerator manager — chip detection, topology, visibility, slices.

Reference: python/ray/_private/accelerators/tpu.py:345 (TPUAcceleratorManager):
resource name "TPU", TPU_VISIBLE_CHIPS, GCE-metadata topology detection
(tpu.py:125), pod-type inference (tpu.py:204). Here TPU is first-class: the
scheduler, worker pool and placement groups all understand chips and
pod-slice head resources natively.
"""

from __future__ import annotations

import glob
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TPU_RESOURCE_NAME = "TPU"
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GKE/GCE env hints (reference tpu.py: TPU_ACCELERATOR_TYPE / metadata server)
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5litepod-16"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_NAME_ENV = "TPU_NAME"
TPU_TOPOLOGY_ENV = "TPU_TOPOLOGY"  # e.g. "4x4"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
# test/dev override
FAKE_TPU_CHIPS_ENV = "RAY_TPU_FAKE_CHIPS"

# generation -> chips per host (single-host VM); reference tpu.py pod-type math
_CHIPS_PER_HOST: Dict[str, int] = {
    "v2": 4,
    "v3": 4,
    "v4": 4,
    "v5litepod": 4,
    "v5p": 4,
    "v6e": 4,
    "v7x": 4,
}

# accelerator-type string constants (reference:
# python/ray/util/accelerators/accelerators.py:32-38)
TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"
TPU_V7X = "TPU-V7X"


def _detect_chips_from_devfs() -> int:
    """Count TPU chips from /dev (accel or vfio), like the reference's
    _get_current_node_tpu_chips (tpu.py)."""
    for pattern in ("/dev/accel*", "/dev/vfio/*"):
        paths = [p for p in glob.glob(pattern) if not p.endswith("vfio")]
        if paths:
            return len(paths)
    return 0


def _detect_chips_from_jax() -> int:
    """Last-resort detection via an initialized jax runtime — only if a
    backend ALREADY exists. jax.devices() on a cold runtime would
    initialize the platform plugin here, inside resource detection: slow
    at best, and a remote/tunneled TPU runtime that is down blocks
    ray_tpu.init() indefinitely."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        from jax._src import xla_bridge as _xb

        if not getattr(_xb, "_backends", None):
            return 0  # no backend initialized; never trigger init here
        return len([d for d in jax.devices() if "tpu" in d.platform.lower() or "TPU" in str(d)])
    except Exception:
        return 0


def parse_pod_type(accelerator_type: str) -> Tuple[str, int]:
    """'v5litepod-16' -> ('v5litepod', 16 chips)."""
    m = re.match(r"^(v\d+[a-z]*(?:pod)?)-(\d+)$", accelerator_type)
    if not m:
        raise ValueError(f"Unrecognized TPU accelerator type: {accelerator_type}")
    return m.group(1), int(m.group(2))


def pod_type_to_ray_accelerator_type(accelerator_type: str) -> str:
    gen = parse_pod_type(accelerator_type)[0]
    return {
        "v2": TPU_V2,
        "v3": TPU_V3,
        "v4": TPU_V4,
        "v5litepod": TPU_V5E,
        "v5p": TPU_V5P,
        "v6e": TPU_V6E,
        "v7x": TPU_V7X,
    }.get(gen, f"TPU-{gen.upper()}")


def num_hosts_in_slice(accelerator_type: str) -> int:
    gen, chips = parse_pod_type(accelerator_type)
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    return max(1, chips // per_host)


def slice_head_resource_name(accelerator_type: str) -> str:
    """The whole-slice gang resource, e.g. 'TPU-v5litepod-16-head'
    (reference: tpu.py — TPU-{pod_type}-head used by SlicePlacementGroup)."""
    return f"TPU-{accelerator_type}-head"


class TPUAcceleratorManager:
    @staticmethod
    def get_resource_name() -> str:
        return TPU_RESOURCE_NAME

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        fake = os.environ.get(FAKE_TPU_CHIPS_ENV)
        if fake:
            return int(fake)
        n = _detect_chips_from_devfs()
        if n:
            return n
        return _detect_chips_from_jax()

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        at = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if at:
            try:
                return pod_type_to_ray_accelerator_type(at)
            except ValueError:
                return None
        return None

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        v = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if v is None:
            return None
        if v == "":
            return []
        return v.split(",")

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)
        # jax reads TPU_VISIBLE_DEVICES / TPU_CHIPS_PER_PROCESS_BOUNDS for
        # subsetting a host's chips; mirror for libtpu consumers.
        os.environ["TPU_VISIBLE_DEVICES"] = os.environ[TPU_VISIBLE_CHIPS_ENV]

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> Tuple[bool, str]:
        if quantity != int(quantity):
            return False, "TPU resource quantity must be whole chips"
        return True, ""

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Expose the slice-head resource on worker 0 of a pod slice
        (reference: tpu.py — only worker 0 advertises TPU-{pod}-head)."""
        out: Dict[str, float] = {}
        at = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
        if not at:
            return out
        worker_id = os.environ.get(TPU_WORKER_ID_ENV)
        try:
            if worker_id is None or worker_id == "0":
                out[slice_head_resource_name(at)] = 1.0
            out[f"accelerator_type:{pod_type_to_ray_accelerator_type(at)}"] = 1.0
        except ValueError:
            logger.warning("Unrecognized TPU_ACCELERATOR_TYPE=%r; ignoring", at)
        return out
