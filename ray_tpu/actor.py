"""ActorClass / ActorHandle / ActorMethod — ``@ray_tpu.remote`` on classes.

Reference: python/ray/actor.py (ActorClass :1543, _remote :1873,
ActorMethod :848, ActorHandle :2252, _actor_method_call :2456).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.core import ActorOptions, TaskOptions, normalize_resources
from ray_tpu._private.ids import ActorID
from ray_tpu.remote_function import _strategy_from_option


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.{self._method_name}.remote()'."
        )

    def options(self, **opts) -> "ActorMethod":
        m = ActorMethod(self._handle, self._method_name, opts.get("num_returns", self._num_returns))
        m._extra_opts = opts
        return m

    def remote(self, *args, **kwargs):
        opts = getattr(self, "_extra_opts", {})
        return self._handle._actor_method_call(
            self._method_name,
            args,
            kwargs,
            num_returns=opts.get("num_returns", self._num_returns),
        )

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(
        self,
        actor_id: ActorID,
        method_names=None,
        actor_class_name: str = "",
        method_opts: Optional[Dict[str, Dict[str, Any]]] = None,
    ):
        self._actor_id = actor_id
        self._method_names = list(method_names or [])
        self._actor_class_name = actor_class_name
        self._method_opts = dict(method_opts or {})

    @classmethod
    def _from_actor_id(cls, actor_id: ActorID) -> "ActorHandle":
        return cls(actor_id)

    def __getattr__(self, item: str) -> ActorMethod:
        if item == "__ray_call__":
            # run an arbitrary function against the actor instance
            # (reference: the injected __ray_call__ actor method):
            # handle.__ray_call__.remote(fn, *args) executes
            # fn(instance, *args) on the actor
            return ActorMethod(self, "__ray_call__", num_returns=1)
        if item.startswith("_"):
            raise AttributeError(item)
        opts = self._method_opts.get(item, {})
        return ActorMethod(self, item, num_returns=opts.get("num_returns", 1))

    def _actor_method_call(self, method_name: str, args, kwargs, num_returns=1):
        w = worker_mod._require_connected()
        opts = TaskOptions(num_returns=num_returns)
        out = w.core.submit_actor_task(self, method_name, args, kwargs, opts)
        if num_returns == "streaming":
            return out  # ObjectRefGenerator
        if num_returns == 1:
            return out[0]
        return out

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._method_names, self._actor_class_name, self._method_opts),
        )

    def __repr__(self) -> str:
        return f"ActorHandle({self._actor_class_name}, {self._actor_id.hex()[:16]})"


class ActorClass:
    def __init__(self, cls: type, actor_options: Dict[str, Any]):
        self._cls = cls
        self._name = cls.__name__
        self._module = cls.__module__ or "__main__"
        self._default_options = dict(actor_options)
        self.__doc__ = cls.__doc__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._name}' cannot be instantiated directly; "
            f"use '{self._name}.remote()'."
        )

    def options(self, **actor_options) -> "_ActorClassProxy":
        merged = dict(self._default_options)
        merged.update(actor_options)
        return _ActorClassProxy(self, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def _build_opts(self, o: Dict[str, Any]) -> ActorOptions:
        resources = normalize_resources(
            o.get("num_cpus"),
            o.get("num_gpus"),
            o.get("num_tpus"),
            o.get("resources"),
            o.get("memory"),
            default_cpus=o.get("num_cpus", 1.0) if o.get("num_cpus") is not None else 1.0,
        )
        return ActorOptions(
            resources=resources,
            max_restarts=int(o.get("max_restarts", 0)),
            max_task_retries=int(o.get("max_task_retries", 0)),
            max_concurrency=int(o.get("max_concurrency", 1)),
            max_pending_calls=int(o.get("max_pending_calls", -1)),
            name=o.get("name"),
            namespace=o.get("namespace"),
            lifetime=o.get("lifetime"),
            get_if_exists=bool(o.get("get_if_exists", False)),
            scheduling_strategy=_strategy_from_option(o.get("scheduling_strategy")),
            runtime_env=o.get("runtime_env") or {},
            cpu_scheduling_only=o.get("num_cpus") is None,
        )

    def _remote(self, args, kwargs, actor_options: Dict[str, Any]) -> ActorHandle:
        w = worker_mod._require_connected()
        opts = self._build_opts(actor_options)
        actor_id = w.core.create_actor(self, args, kwargs, opts)
        methods = []
        method_opts: Dict[str, Dict[str, Any]] = {}
        for m in dir(self._cls):
            if m.startswith("_"):
                continue
            fn = getattr(self._cls, m, None)
            if callable(fn):
                methods.append(m)
                mo = dict(getattr(fn, "__ray_tpu_method_opts__", None) or {})
                if inspect.isgeneratorfunction(fn):
                    # generator methods stream their yields
                    mo.setdefault("num_returns", "streaming")
                if mo:
                    method_opts[m] = mo
        return ActorHandle(actor_id, methods, self._name, method_opts)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs, self._default_options)


class _ActorClassProxy:
    def __init__(self, ac: ActorClass, options: Dict[str, Any]):
        self._ac = ac
        self._options = options

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._ac._remote(args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self._ac, args, kwargs, self._options)


def method(**opts):
    """``@ray_tpu.method(num_returns=n)`` decorator on actor methods
    (reference: python/ray/actor.py method decorator)."""

    def decorator(f):
        f.__ray_tpu_method_opts__ = opts
        return f

    return decorator
