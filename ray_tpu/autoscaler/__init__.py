"""ray_tpu.autoscaler — demand-driven cluster scaling (reference:
python/ray/autoscaler/v2).

The GCS aggregates queued lease shapes from raylet heartbeats plus
pending actors; the autoscaler bin-packs unmet demand onto configured
node types and drives a NodeProvider. TPU pod slices scale atomically
(slice_hosts hosts per unit, terminated only when every host is idle).
"""

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    NodeTypeConfig,
    compute_scaling_decision,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    GCETpuNodeProvider,
    LocalNodeProvider,
    NodeProvider,
)

__all__ = [
    "Autoscaler",
    "FakeNodeProvider",
    "GCETpuNodeProvider",
    "LocalNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "compute_scaling_decision",
]
