"""Autoscaler v2-style reconciler (reference:
python/ray/autoscaler/v2/autoscaler.py + scheduler.py).

One reconcile step:
1. read demand from the GCS (queued lease shapes per node + pending
   actors — `GetClusterDemand`, fed by raylet heartbeats),
2. simulate packing that demand onto the live nodes' available
   resources (first-fit decreasing),
3. bin-pack the unmet remainder onto hypothetical nodes of the
   configured types → launch decisions, bounded by max_workers,
4. terminate nodes idle longer than ``idle_timeout_s`` (never the head,
   never below min_workers).

TPU slices are atomic: a node type with ``slice_hosts > 1`` launches
that many host nodes per unit (all sharing a ``slice_id`` label) and is
only ever terminated whole — one busy host pins the entire slice
(SURVEY.md §7 'slice-granular gang scheduling', util/tpu.py:420).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger("ray_tpu.autoscaler")


@dataclasses.dataclass
class NodeTypeConfig:
    """Reference: available_node_types in the cluster YAML
    (autoscaler/_private/util.py)."""

    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    slice_hosts: int = 1  # >1 = TPU pod slice: launch/terminate atomically
    node_config: Dict = dataclasses.field(default_factory=dict)


def _fits(shape: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _subtract(avail: Dict[str, float], shape: Dict[str, float]) -> None:
    for k, v in shape.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


def compute_scaling_decision(
    demand: dict,
    node_types: Dict[str, NodeTypeConfig],
    type_counts: Dict[str, int],
    idle_timeout_s: float = 60.0,
    node_slices: Optional[Dict[str, str]] = None,
    node_type_map: Optional[Dict[str, str]] = None,
    booting: Optional[Dict[str, int]] = None,
) -> Tuple[Dict[str, int], List[str]]:
    """Pure decision function (unit-testable without a cluster).

    demand: GetClusterDemand reply. type_counts: live worker count per
    node type (in slice units for slice types). node_slices: node_id →
    slice_id for slice-grouped termination. node_type_map: node_id →
    node type, used to hold min_workers through idle termination.
    booting: units per type already launched but not yet registered in
    the GCS — their capacity is credited so each reconcile round doesn't
    re-launch for the same demand (reference: the v2 instance manager
    tracks pending instances).
    Returns (launch: {type: units}, terminate: [node_ids]).
    """
    node_slices = node_slices or {}
    node_type_map = node_type_map or {}
    booting = booting or {}
    # DRAINING nodes are on their way out: their capacity must not
    # absorb simulated demand (it would under-launch), and they are
    # never idle-termination candidates (already terminating)
    nodes = [n for n in demand.get("nodes", [])
             if n.get("alive") and not n.get("draining")]
    shapes: List[Dict[str, float]] = []
    for n in nodes:
        shapes.extend(n.get("pending_shapes", []))
    shapes.extend(demand.get("pending_actors", []))
    # drop zero/empty shapes; first-fit decreasing by total magnitude
    shapes = [s for s in shapes if any(v > 0 for v in s.values())]
    shapes.sort(key=lambda s: -sum(s.values()))

    # 1) what the live cluster can already absorb
    avails = [dict(n["available"]) for n in nodes]
    unmet: List[Dict[str, float]] = []
    for s in shapes:
        for a in avails:
            if _fits(s, a):
                _subtract(a, s)
                break
        else:
            unmet.append(s)

    # 2) pack the unmet remainder onto hypothetical new nodes; nodes
    # still booting count as capacity first
    launch: Dict[str, int] = {}
    pending_avails: List[Dict[str, float]] = []
    for tname, units in booting.items():
        tc = node_types.get(tname)
        if tc is None:
            continue
        for _ in range(units * tc.slice_hosts):
            pending_avails.append(dict(tc.resources))
    for s in unmet:
        placed = False
        for a in pending_avails:
            if _fits(s, a):
                _subtract(a, s)
                placed = True
                break
        if placed:
            continue
        # smallest type that fits the shape (deterministic order)
        for tname in sorted(
                node_types, key=lambda t: sum(node_types[t].resources.values())):
            tc = node_types[tname]
            if not _fits(s, dict(tc.resources)):
                continue
            if type_counts.get(tname, 0) + launch.get(tname, 0) \
                    >= tc.max_workers:
                continue
            launch[tname] = launch.get(tname, 0) + 1
            # a slice launch adds slice_hosts nodes' worth of capacity
            for _ in range(tc.slice_hosts):
                a = dict(tc.resources)
                pending_avails.append(a)
            _subtract(pending_avails[-tc.slice_hosts], s)
            placed = True
            break
        if not placed:
            logger.warning("demand shape %s is infeasible on all node types", s)

    # 3) honor min_workers
    for tname, tc in node_types.items():
        have = type_counts.get(tname, 0) + launch.get(tname, 0)
        if have < tc.min_workers:
            launch[tname] = launch.get(tname, 0) + (tc.min_workers - have)

    # 4) idle termination — whole slices only, never the head, never
    # below min_workers; never while unmet demand exists (a just-launched
    # node can look idle for a beat before queued leases reach it —
    # terminating it then flaps)
    terminate: List[str] = []
    if unmet or launch:
        return launch, terminate
    # remaining (post-termination) count per type, for min_workers holds
    remaining: Dict[str, int] = dict(type_counts)

    def _may_remove(tname: Optional[str], units: int = 1) -> bool:
        if tname is None or tname not in node_types:
            return True
        if remaining.get(tname, 0) - units < node_types[tname].min_workers:
            return False
        remaining[tname] = remaining.get(tname, 0) - units
        return True

    by_slice: Dict[str, List[dict]] = {}
    solo: List[dict] = []
    for n in nodes:
        if n.get("is_head"):
            continue
        sid = node_slices.get(n["node_id"])
        if sid:
            by_slice.setdefault(sid, []).append(n)
        else:
            solo.append(n)
    for n in solo:
        if n.get("idle_s", 0.0) > idle_timeout_s and \
                _may_remove(node_type_map.get(n["node_id"])):
            terminate.append(n["node_id"])
    for sid, members in by_slice.items():
        if all(m.get("idle_s", 0.0) > idle_timeout_s for m in members) and \
                _may_remove(node_type_map.get(members[0]["node_id"])):
            terminate.extend(m["node_id"] for m in members)
    return launch, terminate


class Autoscaler:
    """Reconcile loop binding the decision function to a provider and a
    live GCS (reference: autoscaler/v2/autoscaler.py)."""

    def __init__(
        self,
        gcs_addr: Tuple[str, int],
        node_types: Dict[str, NodeTypeConfig],
        provider: NodeProvider,
        idle_timeout_s: float = 60.0,
        interval_s: float = 5.0,
    ):
        from ray_tpu._private.rpc import RpcClient

        self.gcs = RpcClient(*gcs_addr)
        self.node_types = dict(node_types)
        self.provider = provider
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        # provider_node_id -> (node_type, slice_id)
        self._launched: Dict[str, Tuple[str, str]] = {}
        self._launch_times: Dict[str, float] = {}
        self.boot_grace_s = 120.0  # credit booting nodes this long
        # graceful-drain deadline for idle terminations (idle nodes hold
        # no leases; the drain is just the deregister handshake)
        self.drain_deadline_s = 5.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_terminations = 0
        # flip lease semantics cluster-wide: infeasible requests queue as
        # demand instead of failing (propagates via heartbeat replies)
        try:
            self.gcs.call("SetAutoscalerEnabled", enabled=True, timeout=10)
        except Exception:  # noqa: BLE001
            logger.warning("could not announce autoscaler to GCS")

    # -- one reconcile step -------------------------------------------
    def update(self) -> Tuple[Dict[str, int], List[str]]:
        # renew the TTL lease each round: survives a GCS restart losing
        # the flag, and expires if this autoscaler dies
        try:
            self.gcs.call("SetAutoscalerEnabled", enabled=True,
                          ttl_s=max(30.0, 3 * self.interval_s), timeout=5)
        except Exception:  # noqa: BLE001
            pass
        demand = self.gcs.call("GetClusterDemand", timeout=10)
        live = self.provider.non_terminated_nodes()
        self._launched = {nid: meta for nid, meta in self._launched.items()
                          if nid in live}
        self._launch_times = {nid: t for nid, t in self._launch_times.items()
                              if nid in self._launched}
        type_counts: Dict[str, int] = {}
        slice_units: Dict[str, set] = {}
        for nid, (tname, sid) in self._launched.items():
            tc = self.node_types.get(tname)
            if tc and tc.slice_hosts > 1:
                slice_units.setdefault(tname, set()).add(sid)
            else:
                type_counts[tname] = type_counts.get(tname, 0) + 1
        for tname, sids in slice_units.items():
            type_counts[tname] = len(sids)
        # map GCS nodes to slice/type via the labels the launch stamped —
        # provider node ids (e.g. GCE VM names) need not equal raylet
        # node ids, labels are the join key
        gcs_nodes = {n["node_id"]: n for n in demand.get("nodes", [])}
        node_slices = {
            nid: n["labels"]["slice_id"]
            for nid, n in gcs_nodes.items()
            if n.get("labels", {}).get("slice_id")
        }
        node_type_map = {
            nid: n["labels"]["node_type"]
            for nid, n in gcs_nodes.items()
            if n.get("labels", {}).get("node_type")
        }
        # nodes we launched that haven't registered in the GCS yet count
        # as booting capacity (until a grace period expires — a node that
        # never comes up stops blocking launches)
        now = time.monotonic()
        booting: Dict[str, int] = {}
        booting_sids: Dict[str, set] = {}
        for nid, (tname, sid) in self._launched.items():
            if nid in gcs_nodes:
                continue
            if now - self._launch_times.get(nid, 0.0) > self.boot_grace_s:
                continue
            tc = self.node_types.get(tname)
            if tc and tc.slice_hosts > 1:
                booting_sids.setdefault(tname, set()).add(sid)
            else:
                booting[tname] = booting.get(tname, 0) + 1
        for tname, sids in booting_sids.items():
            booting[tname] = booting.get(tname, 0) + len(sids)
        launch, terminate = compute_scaling_decision(
            demand, self.node_types, type_counts,
            idle_timeout_s=self.idle_timeout_s, node_slices=node_slices,
            node_type_map=node_type_map, booting=booting)
        for tname, units in launch.items():
            tc = self.node_types[tname]
            for _ in range(units):
                sid = uuid.uuid4().hex[:8]
                cfg = dict(tc.node_config, resources=dict(tc.resources),
                           slice_hosts=tc.slice_hosts)
                ids = self.provider.create_node(
                    tname, cfg, labels={"node_type": tname, "slice_id": sid})
                for nid in ids:
                    self._launched[nid] = (tname, sid)
                    self._launch_times[nid] = time.monotonic()
                self.num_launches += 1
                logger.info("launched %s x1 (%d hosts): %s",
                            tname, len(ids), ids)
        killed: set = set()
        killed_sids: set = set()
        for nid in terminate:
            # resolve the GCS node to provider node(s): direct id match
            # (LocalNodeProvider) or via the slice_id label (cloud
            # providers whose ids are VM names)
            sid = gcs_nodes.get(nid, {}).get("labels", {}).get("slice_id")
            if nid in self._launched:
                pids = [nid]
            else:
                pids = [p for p, (_t, s) in self._launched.items()
                        if sid and s == sid]
            pids = [p for p in pids if p not in killed]
            if not pids and not (sid and sid in killed_sids):
                continue  # not ours (e.g. manually added node)
            # gracefully drain EVERY GCS member of a terminated slice,
            # including those whose provider host was already destroyed
            # by an earlier iteration — otherwise the cluster view keeps
            # spilling leases to a dead host until heartbeat timeout.
            # Idle nodes quiesce in seconds; the short deadline bounds
            # the window before the provider hard-terminates below.
            try:
                from ray_tpu._private.drain import REASON_IDLE_TERMINATION

                self.gcs.call("DrainNode", node_id=nid,
                              reason=REASON_IDLE_TERMINATION,
                              deadline_s=self.drain_deadline_s, timeout=5)
            except Exception:  # noqa: BLE001
                pass
            if sid:
                killed_sids.add(sid)
            for pid in pids:
                self.provider.terminate_node(pid)
                self._launched.pop(pid, None)
                self._launch_times.pop(pid, None)
                killed.add(pid)
                self.num_terminations += 1
                logger.info("terminated idle node %s", str(pid)[:12])
        return launch, terminate

    # -- background loop ----------------------------------------------
    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.update()
                except Exception:  # noqa: BLE001
                    logger.exception("autoscaler update failed")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="ray-tpu-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        try:
            self.gcs.call("SetAutoscalerEnabled", enabled=False, timeout=5)
        except Exception:  # noqa: BLE001
            pass
