"""Node providers — how the autoscaler acquires/releases machines.

Reference: python/ray/autoscaler/node_provider.py (`NodeProvider` ABC)
and autoscaler/v2/instance_manager/cloud_providers/. Three providers:

- ``FakeNodeProvider`` — in-memory bookkeeping for unit tests.
- ``LocalNodeProvider`` — spawns REAL raylet daemons on this machine,
  registering with a live GCS (the cluster_utils.Cluster mechanism) —
  the end-to-end test path and the single-host dev story.
- ``GCETpuNodeProvider`` — shells out to gcloud for TPU VMs; slice
  creation/deletion is atomic at the queued-resource level. Requires a
  GCP environment; methods raise a clear error when gcloud is absent.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Dict, List, Optional, Tuple


class NodeProvider:
    """Minimal provider contract. ``create_node`` returns provider node
    ids — for TPU slice types one create call may return SEVERAL host
    nodes (the slice is atomic: all hosts or none)."""

    def create_node(self, node_type: str, node_config: dict,
                    labels: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider_node_id -> node_type"""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    def __init__(self):
        self.launches: List[Tuple[str, dict]] = []
        self.terminated: List[str] = []
        self._nodes: Dict[str, str] = {}
        self._n = 0

    def create_node(self, node_type, node_config, labels):
        count = int(node_config.get("slice_hosts", 1))
        ids = []
        for _ in range(count):
            self._n += 1
            nid = f"fake-{node_type}-{self._n}"
            self._nodes[nid] = node_type
            ids.append(nid)
        self.launches.append((node_type, dict(node_config)))
        return ids

    def terminate_node(self, provider_node_id):
        self._nodes.pop(provider_node_id, None)
        self.terminated.append(provider_node_id)

    def non_terminated_nodes(self):
        return dict(self._nodes)


class LocalNodeProvider(NodeProvider):
    """Real raylet daemons joining an existing GCS — provider node id ==
    raylet node id, so the autoscaler can match GCS state directly."""

    def __init__(self, gcs_addr: Tuple[str, int],
                 session_dir: Optional[str] = None):
        self.gcs_addr = tuple(gcs_addr)
        self.session_dir = session_dir or tempfile.mkdtemp(
            prefix="ray_tpu_autoscaler_")
        self._nodes: Dict[str, Tuple[subprocess.Popen, str]] = {}

    def create_node(self, node_type, node_config, labels):
        from ray_tpu._private.config import config
        from ray_tpu._private.ids import NodeID
        from ray_tpu._private.node import spawn_raylet

        count = int(node_config.get("slice_hosts", 1))
        ids = []
        for _ in range(count):
            node_id = NodeID.from_random().hex()
            node_dir = os.path.join(self.session_dir,
                                    f"as-{node_type}-{node_id[:8]}")
            os.makedirs(node_dir, exist_ok=True)
            res = dict(node_config.get("resources") or {"CPU": 1.0})
            res.setdefault("memory", 1 * 1024**3)
            proc, _port = spawn_raylet(
                gcs_addr=self.gcs_addr,
                node_id=node_id,
                resources=res,
                store_socket=os.path.join(node_dir, "store.sock"),
                store_capacity=int(
                    node_config.get("object_store_memory")
                    or config.object_store_memory_bytes),
                session_dir=node_dir,
                is_head=False,
                labels=dict(labels),
            )
            self._nodes[node_id] = (proc, node_type)
            ids.append(node_id)
        return ids

    def terminate_node(self, provider_node_id):
        from ray_tpu._private.node import kill_process_tree

        ent = self._nodes.pop(provider_node_id, None)
        if ent is not None:
            kill_process_tree(ent[0])

    def non_terminated_nodes(self):
        return {nid: t for nid, (p, t) in self._nodes.items()
                if p.poll() is None}

    def shutdown(self) -> None:
        for nid in list(self._nodes):
            self.terminate_node(nid)


class GCETpuNodeProvider(NodeProvider):
    """TPU-VM provider via gcloud (reference: autoscaler/_private/gcp/
    node_provider.py + the TPU queued-resources API). A node type whose
    config carries ``accelerator_type`` (e.g. "v5litepod-16") maps to
    ONE TPU slice; create/delete operate on whole slices — hosts of a
    slice never scale independently (SURVEY.md §7 'slice-granular gang
    scheduling').

    ``head_address`` (GCS host:port reachable from the VMs) is required
    for the VM to JOIN the cluster: a startup script runs
    ``ray-tpu start --address`` on every host with the launch labels, so
    the raylets register carrying node_type/slice_id — the autoscaler's
    join key for matching GCS nodes back to VMs. ``setup_command``
    prepends e.g. a pip install of this package."""

    def __init__(self, project: str, zone: str, head_address: str,
                 prefix: str = "ray-tpu", setup_command: str = ""):
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.prefix = prefix
        self.setup_command = setup_command
        self._n = 0
        # name → node_type, recorded at create time: parsing the type back
        # out of the VM name breaks for dashed type keys / custom prefixes
        self._name_to_type: Dict[str, str] = {}

    def _gcloud(self, *args: str) -> str:
        try:
            return subprocess.check_output(
                ("gcloud",) + args, text=True,
                stderr=subprocess.STDOUT)
        except FileNotFoundError as e:
            raise RuntimeError(
                "gcloud CLI not available — GCETpuNodeProvider needs a "
                "GCP environment") from e

    def _startup_script(self, node_config: dict,
                        labels: Dict[str, str]) -> str:
        import json as _json
        import shlex

        resources = node_config.get("resources") or {}
        return "\n".join([
            "#! /bin/bash",
            self.setup_command,
            "python3 -m ray_tpu.scripts.scripts start "
            f"--address {shlex.quote(self.head_address)} "
            f"--labels {shlex.quote(_json.dumps(labels))} "
            + (f"--num-cpus {resources['CPU']} "
               if resources.get("CPU") else "")
            + (f"--num-tpus {resources['TPU']}"
               if resources.get("TPU") else ""),
        ])

    def create_node(self, node_type, node_config, labels):
        self._n += 1
        name = f"{self.prefix}-{node_type}-{self._n}"
        self._name_to_type[name] = node_type
        acc = node_config["accelerator_type"]
        # --metadata splits on commas (the JSON labels always contain
        # one) — the script must go through --metadata-from-file
        with tempfile.NamedTemporaryFile(
                "w", suffix=".sh", delete=False) as f:
            f.write(self._startup_script(node_config, labels))
            script_path = f.name
        try:
            self._gcloud(
                "compute", "tpus", "tpu-vm", "create", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--accelerator-type={acc}",
                f"--version={node_config.get('runtime_version', 'tpu-ubuntu2204-base')}",
                f"--metadata-from-file=startup-script={script_path}",
            )
        finally:
            try:
                os.unlink(script_path)
            except OSError:
                pass
        return [name]

    def terminate_node(self, provider_node_id):
        self._gcloud(
            "compute", "tpus", "tpu-vm", "delete", provider_node_id,
            f"--project={self.project}", f"--zone={self.zone}", "--quiet",
        )
        self._name_to_type.pop(provider_node_id, None)

    def non_terminated_nodes(self):
        out = self._gcloud(
            "compute", "tpus", "tpu-vm", "list",
            f"--project={self.project}", f"--zone={self.zone}",
            "--format=value(name)",
        )
        return {n: self._name_to_type.get(n, self._parse_type(n))
                for n in out.split() if n.startswith(self.prefix + "-")}

    def _parse_type(self, name: str) -> str:
        # nodes created by an earlier provider incarnation: strip the
        # "<prefix>-" head and the "-<counter>" tail; what remains is the
        # type key even when it contains dashes
        body = name[len(self.prefix) + 1:]
        head, _, tail = body.rpartition("-")
        return head if head and tail.isdigit() else body
