"""In-process multi-node test cluster.

Reference: python/ray/cluster_utils.py (Cluster :141, add_node :208) — starts
one GCS plus N raylet daemons (each with its own shared-memory store and
worker pool) as local processes, so multi-node scheduling, object transfer
and fault-tolerance are testable on a single machine.

Usage:
    cluster = Cluster()
    cluster.add_node(num_cpus=2)              # head node
    n2 = cluster.add_node(num_cpus=2, resources={"worker2": 1})
    ray_tpu.init(address=cluster.address)
    ...
    cluster.remove_node(n2)                   # simulates node failure
    cluster.shutdown()
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import config
from ray_tpu._private.ids import NodeID
from ray_tpu._private.node import kill_process_tree, spawn_gcs, spawn_raylet
from ray_tpu._private.rpc import RpcClient


@dataclass
class ClusterNode:
    node_id: str
    proc: subprocess.Popen
    raylet_port: int
    store_socket: str
    session_dir: str
    resources: Dict[str, float] = field(default_factory=dict)
    is_head: bool = False

    @property
    def raylet_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.raylet_port)


class Cluster:
    """One GCS + N raylets on this machine, each raylet a real daemon
    process owning its own object store and workers."""

    def __init__(self, gcs_storage: bool = False):
        """gcs_storage=True enables file-backed GCS persistence so
        ``restart_gcs()`` replays state (reference: GCS fault tolerance
        over Redis, gcs_init_data.h)."""
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_cluster_")
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.gcs_port: Optional[int] = None
        self.nodes: List[ClusterNode] = []
        self.gcs_storage_path = (
            os.path.join(self.session_dir, "gcs_state.bin")
            if gcs_storage else "")
        self._start_gcs()

    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.gcs_port}"

    @property
    def gcs_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.gcs_port)

    def _start_gcs(self) -> None:
        import socket

        if self.gcs_port is None:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            self.gcs_port = s.getsockname()[1]
            s.close()
        old = config.gcs_storage_path
        try:
            if self.gcs_storage_path:
                config.gcs_storage_path = self.gcs_storage_path
            self.gcs_proc = spawn_gcs(self.gcs_port, self.session_dir)
        finally:
            config.gcs_storage_path = old

    def kill_gcs(self) -> None:
        """Kill the GCS process (simulating a control-plane crash)."""
        if self.gcs_proc is not None:
            kill_process_tree(self.gcs_proc, force=True)
            self.gcs_proc = None

    def restart_gcs(self) -> None:
        """Restart the GCS on the SAME port; with gcs_storage it replays
        its persisted tables and raylets re-register via heartbeats."""
        self.kill_gcs()
        self._start_gcs()

    # ------------------------------------------------------------------
    def add_node(
        self,
        num_cpus: float = 1.0,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> ClusterNode:
        node_id = NodeID.from_random().hex()
        node_dir = os.path.join(self.session_dir, f"node-{len(self.nodes)}-{node_id[:8]}")
        os.makedirs(node_dir, exist_ok=True)
        store_socket = os.path.join(node_dir, "store.sock")
        res: Dict[str, float] = dict(resources or {})
        res["CPU"] = float(num_cpus)
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.setdefault("memory", 1 * 1024**3)
        res["node:127.0.0.1"] = 1.0
        is_head = not self.nodes
        proc, port = spawn_raylet(
            gcs_addr=self.gcs_addr,
            node_id=node_id,
            resources=res,
            store_socket=store_socket,
            store_capacity=int(object_store_memory or config.object_store_memory_bytes),
            session_dir=node_dir,
            is_head=is_head,
            labels=labels,
        )
        node = ClusterNode(
            node_id=node_id,
            proc=proc,
            raylet_port=port,
            store_socket=store_socket,
            session_dir=node_dir,
            resources=res,
            is_head=is_head,
        )
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode, allow_graceful: bool = False,
                    drain_deadline_s: float = 5.0) -> None:
        """Kill a node's raylet (and its store + workers), simulating node
        failure. ``allow_graceful`` runs the full drain protocol first
        (_private/drain.py): the node stops taking leases, in-flight work
        finishes or migrates, and the raylet deregisters and exits on its
        own — the kill below is only the backstop. Without it the GCS
        notices via missed heartbeats, as for a crash."""
        if allow_graceful:
            from ray_tpu._private.drain import REASON_IDLE_TERMINATION

            client = RpcClient("127.0.0.1", self.gcs_port)
            try:
                client.call(
                    "DrainNode", node_id=node.node_id,
                    reason=REASON_IDLE_TERMINATION,
                    deadline_s=drain_deadline_s, timeout=5,
                )
                deadline = time.monotonic() + drain_deadline_s + 3.0
                while time.monotonic() < deadline:
                    if node.proc.poll() is not None:
                        break
                    time.sleep(0.05)
            except Exception:
                pass
            finally:
                client.close()
        kill_process_tree(node.proc, force=not allow_graceful)
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every added node is registered and alive in the GCS."""
        client = RpcClient("127.0.0.1", self.gcs_port)
        try:
            want = {n.node_id for n in self.nodes}
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    infos = client.call("GetAllNodeInfo", timeout=5)
                    alive = {n["NodeID"] for n in infos if n["Alive"]}
                    if want <= alive:
                        return
                except Exception:
                    pass
                time.sleep(0.1)
            raise TimeoutError(f"nodes did not come up: want {want}")
        finally:
            client.close()

    def shutdown(self) -> None:
        """Tear the cluster down via a short graceful drain, then kill.
        Draining first quiesces lease grants and worker spawns, so the
        kills below land on idle daemons instead of racing in-flight
        RPCs (the shutdown-order "Task was destroyed" class of noise on
        busy clusters); the CLUSTER_SHUTDOWN reason skips the object
        push — nobody is left to read the copies."""
        if self.gcs_proc is not None and self.nodes:
            from ray_tpu._private.drain import REASON_CLUSTER_SHUTDOWN

            client = RpcClient("127.0.0.1", self.gcs_port)
            try:
                for node in self.nodes:
                    client.call(
                        "DrainNode", node_id=node.node_id,
                        reason=REASON_CLUSTER_SHUTDOWN,
                        deadline_s=0.2, timeout=2,
                    )
                # brief window for the raylets to quiesce and self-exit
                deadline = time.monotonic() + 1.5
                while time.monotonic() < deadline and any(
                        n.proc.poll() is None for n in self.nodes):
                    time.sleep(0.05)
            except Exception:
                pass  # best-effort quiesce; kill_process_tree is the backstop
            finally:
                client.close()
        for node in list(self.nodes):
            kill_process_tree(node.proc)
        self.nodes.clear()
        kill_process_tree(self.gcs_proc)
        self.gcs_proc = None
