"""Lazy task/actor DAG building + execution.

Reference: python/ray/dag/ (DAGNode, FunctionNode, ClassNode, InputNode;
compiled DAGs in compiled_dag_node.py). ``f.bind(x).execute()`` submits the
underlying tasks with dependencies expressed as ObjectRefs;
``.experimental_compile()`` returns a CompiledDAG (ray_tpu/dag_compiled.py)
whose schedule and actors are fixed once and reused across executions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a deferred computation with upstream deps."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._cache: Optional[Any] = None

    # -- traversal -----------------------------------------------------
    def _resolve_arg(self, v, input_value):
        if isinstance(v, DAGNode):
            return v._execute_impl(input_value)
        return v

    def _resolved(self, input_value) -> Tuple[tuple, dict]:
        args = tuple(self._resolve_arg(a, input_value) for a in self._bound_args)
        kwargs = {k: self._resolve_arg(v, input_value) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_impl(self, input_value):
        raise NotImplementedError

    def execute(self, *input_values):
        """Execute the DAG; returns ObjectRef(s) for the terminal node."""
        input_value = input_values[0] if input_values else None
        self._clear_cache()
        return self._execute_impl(input_value)

    def _clear_cache(self):
        self._cache = None
        for v in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                v._clear_cache()

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag_compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder for the runtime input (reference: ray.dag.InputNode)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, input_value):
        return input_value


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs, options: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = options

    def _execute_impl(self, input_value):
        if self._cache is None:
            args, kwargs = self._resolved(input_value)
            self._cache = self._remote_fn._remote(args, kwargs, self._options)
        return self._cache


class ClassNode(DAGNode):
    """A bound actor-class instantiation."""

    def __init__(self, actor_cls, args, kwargs, options: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._options = options
        self._handle = None

    def _execute_impl(self, input_value):
        if self._handle is None:
            args, kwargs = self._resolved(input_value)
            self._handle = self._actor_cls._remote(args, kwargs, self._options)
        return self._handle

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _ClassMethodBinder(self, item)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs):
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _execute_impl(self, input_value):
        if self._cache is None:
            handle = self._class_node._execute_impl(input_value)
            args, kwargs = self._resolved(input_value)
            self._cache = handle._actor_method_call(self._method_name, args, kwargs)
        return self._cache


class ActorMethodNode(DAGNode):
    """bind() on a live ActorHandle's method."""

    def __init__(self, handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method_name = method_name

    def _execute_impl(self, input_value):
        if self._cache is None:
            args, kwargs = self._resolved(input_value)
            self._cache = self._handle._actor_method_call(self._method_name, args, kwargs)
        return self._cache


class MultiOutputNode(DAGNode):
    """Terminal wrapper returning every member's result
    (reference: ray.dag.MultiOutputNode)."""

    def __init__(self, nodes):
        super().__init__(tuple(nodes), {})

    def __iter__(self):
        return iter(self._bound_args)

    def __len__(self):
        return len(self._bound_args)

    def _execute_impl(self, input_value):
        return [n._execute_impl(input_value) for n in self._bound_args]

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag_compiled import CompiledDAG

        return CompiledDAG(list(self._bound_args), **kwargs)
