"""Compiled DAG execution (reference: python/ray/dag/compiled_dag_node.py:813
CompiledDAG).

The reference pre-compiles an actor-task DAG into static shared-memory
channels plus a per-actor execution schedule, so a steady-state `execute()`
does no Python-side graph work. The TPU-first reading (SURVEY.md §2.3): the
*device* side of an aDAG is already compiled by XLA inside each jitted
actor method; what the framework owns is the host-side schedule. Compiling
here means:

- the DAG is validated and topologically ordered ONCE,
- ClassNodes instantiate their actors ONCE (reused across executes),
- per-node argument wiring is precomputed (which upstream output / which
  constant feeds each slot), so execute() is a flat loop of task
  submissions with ObjectRef dependencies — no graph traversal, no
  node-cache invalidation, no re-pickling of bound constants.

Multiple executions may be in flight concurrently; each returns fresh
ObjectRefs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag import (
    ActorMethodNode,
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)


class _Slot:
    """Where one argument of a compiled node comes from."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: Any):
        self.kind = kind  # "const" | "node" | "input"
        self.value = value  # constant | node index | None


class CompiledDAG:
    """Host-side compiled schedule for a DAG (reference:
    compiled_dag_node.py:813)."""

    def __init__(self, root, **_kwargs):
        self._outputs: List[DAGNode] = list(root) if isinstance(root, list) else [root]
        self._multi = isinstance(root, list)
        self._nodes: List[DAGNode] = []
        self._index: Dict[int, int] = {}  # id(node) -> schedule position
        self._slots: List[Tuple[List[_Slot], Dict[str, _Slot]]] = []
        self._handles: Dict[int, Any] = {}  # schedule pos of ClassNode -> actor
        self._torn_down = False
        for out in self._outputs:
            self._visit(out)
        self._compile()

    # -- compile --------------------------------------------------------
    def _visit(self, node: DAGNode) -> int:
        if id(node) in self._index:
            return self._index[id(node)]
        if isinstance(node, ClassMethodNode):
            self._visit(node._class_node)
        for v in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                self._visit(v)
        pos = len(self._nodes)
        self._index[id(node)] = pos
        self._nodes.append(node)
        return pos

    def _slot(self, v) -> _Slot:
        if isinstance(v, InputNode):
            return _Slot("input", None)
        if isinstance(v, DAGNode):
            return _Slot("node", self._index[id(v)])
        return _Slot("const", v)

    def _compile(self) -> None:
        n_inputs = sum(1 for n in self._nodes if isinstance(n, InputNode))
        if n_inputs > 1:
            raise ValueError("compiled DAGs support at most one InputNode")
        for node in self._nodes:
            args = [self._slot(a) for a in node._bound_args]
            kwargs = {k: self._slot(v) for k, v in node._bound_kwargs.items()}
            self._slots.append((args, kwargs))
            if isinstance(node, ClassNode):
                # actors are part of the compiled graph: created once here
                pos = self._index[id(node)]
                cargs = [s.value for s in args]
                if any(s.kind != "const" for s in args) or any(
                    s.kind != "const" for s in kwargs.values()
                ):
                    raise ValueError(
                        "compiled ClassNode constructor args must be constants"
                    )
                self._handles[pos] = node._actor_cls._remote(
                    tuple(cargs), {k: s.value for k, s in kwargs.items()},
                    node._options,
                )

    # -- execute --------------------------------------------------------
    def execute(self, *input_values):
        if self._torn_down:
            raise RuntimeError("CompiledDAG was torn down")
        input_value = input_values[0] if input_values else None
        results: List[Any] = [None] * len(self._nodes)

        def resolve(slot: _Slot):
            if slot.kind == "const":
                return slot.value
            if slot.kind == "input":
                return input_value
            return results[slot.value]

        for pos, node in enumerate(self._nodes):
            arg_slots, kwarg_slots = self._slots[pos]
            if isinstance(node, InputNode):
                results[pos] = input_value
                continue
            if isinstance(node, ClassNode):
                results[pos] = self._handles[pos]
                continue
            args = tuple(resolve(s) for s in arg_slots)
            kwargs = {k: resolve(s) for k, s in kwarg_slots.items()}
            if isinstance(node, FunctionNode):
                results[pos] = node._remote_fn._remote(args, kwargs, node._options)
            elif isinstance(node, ClassMethodNode):
                handle = self._handles[self._index[id(node._class_node)]]
                results[pos] = handle._actor_method_call(node._method_name, args, kwargs)
            elif isinstance(node, ActorMethodNode):
                results[pos] = node._handle._actor_method_call(
                    node._method_name, args, kwargs
                )
            else:
                raise TypeError(f"cannot compile node type {type(node).__name__}")
        outs = [results[self._index[id(o)]] for o in self._outputs]
        return outs if self._multi else outs[0]

    def teardown(self) -> None:
        """Kill actors this compiled DAG created (reference:
        CompiledDAG.teardown)."""
        import ray_tpu

        self._torn_down = True
        for handle in self._handles.values():
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        self._handles.clear()
