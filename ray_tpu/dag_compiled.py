"""Compiled DAG execution (reference: python/ray/dag/compiled_dag_node.py:813
CompiledDAG + experimental/channel/shared_memory_channel.py).

The reference pre-compiles an actor-task DAG into static shared-memory
channels plus a per-actor execution schedule, so a steady-state
``execute()`` does NO task submission: the driver writes the input
channel, each actor runs a persistent loop (read input channels →
execute method → write output channel), and the driver reads the output
channels. The TPU-first reading (SURVEY.md §2.3): the *device* side of
an aDAG is already compiled by XLA inside each jitted actor method; the
framework owns the host-side steady state, and that is exactly what the
channels carry.

Compiling here means:
- the DAG is validated and topologically ordered ONCE,
- ClassNodes instantiate their actors ONCE; FunctionNodes get a
  dedicated executor actor so every compute node lives in a persistent
  process,
- one shm channel per cross-actor edge + per DAG output + ONE input
  channel; same-actor edges pass values in memory,
- each actor is sent ONE ``__ray_call__`` exec-loop task that serves
  every subsequent ``execute()`` — the task RPC path is not touched
  again.

``execute()`` returns a :class:`CompiledDAGRef`; ``ray_tpu.get`` (or
``.get()``) blocks on the output channels. Executions pipeline: the
driver may run ahead of the actors by one value per channel (the
channels' ack backpressure bounds the pipeline depth, reference:
shared_memory_channel.py buffering).

If channel setup fails — e.g. an actor lives on another node where the
driver's shm segments don't resolve — compilation falls back to the
task-submission path (one RPC per node per execute), preserving
behavior at lower throughput.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag import (
    ActorMethodNode,
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)
from ray_tpu.experimental.channel import Channel, ChannelTimeoutError

_STOP = "__ray_tpu_dag_stop__"


class _DagErr:
    """A node failure traveling through channels to downstream nodes and
    the driver (reference: exceptions propagate through compiled-DAG
    channels as values)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data


class _LoopStop(Exception):
    """Raised inside an exec loop when the DAG is being torn down."""


def _mk_err(method_name: str, e: BaseException) -> "_DagErr":
    import traceback

    from ray_tpu.exceptions import RayTaskError

    return _DagErr(pickle.dumps(RayTaskError(
        method_name,
        f"{type(e).__name__}: {e}\n{traceback.format_exc()}")))


def _read_block(reader, stopped):
    """Channel read in short ticks so a teardown signal (the stop
    channel's header advancing) frees even a loop whose upstream died."""
    while True:
        try:
            return reader.read(timeout=2.0)
        except ChannelTimeoutError:
            if stopped():
                raise _LoopStop from None


def _write_block(writer, value, stopped, method_name):
    """Channel write that (a) survives legitimate backpressure — an
    unread output slot is NOT a failure, tick until acked — and (b)
    converts an oversized value into a per-execute _DagErr instead of
    killing the loop."""
    while True:
        try:
            writer.write(value, timeout=2.0)
            return
        except ChannelTimeoutError:
            if stopped():
                raise _LoopStop from None
        except ValueError as e:  # payload exceeds channel capacity
            if isinstance(value, _DagErr):
                raise  # already minimal; give up
            value = _mk_err(method_name, e)


def _dag_exec_loop(instance, ready, input_reader, steps, chan_readers,
                   stop_reader):
    """Persistent per-actor execution loop, sent once via __ray_call__
    (reference: compiled_dag_node.py do_exec_tasks — the per-actor loop
    that replaces task submission in the steady state).

    ``steps``: ordered [(pos, method_name, arg_specs, kwarg_specs,
    writer)]; arg spec kinds: ("c", const) | ("i",) input | ("l", pos)
    same-actor value | ("r", dep_pos) cross-actor channel.
    ``chan_readers``: {dep_pos: ChannelReader} — ONE reader per upstream
    channel; each is read exactly once per iteration (a second read of
    the same value would block on the next sequence forever).
    ``stop_reader``: never read — its header seq advancing is the
    teardown signal every blocking tick polls, so the loop exits even
    when wedged on a dead upstream's edge channel.
    """

    def stopped() -> bool:
        return stop_reader._seq > 0

    ready.write("ready")
    try:
        while True:
            val = _read_block(input_reader, stopped)
            if isinstance(val, str) and val == _STOP:
                return "stopped"
            local: Dict[int, Any] = {}
            remote_vals: Dict[int, Any] = {}
            for pos, method_name, arg_specs, kwarg_specs, writer in steps:

                def _resolve(spec):
                    kind = spec[0]
                    if kind == "c":
                        return spec[1]
                    if kind == "i":
                        return val
                    if kind == "l":
                        return local[spec[1]]
                    dep = spec[1]  # "r"
                    if dep not in remote_vals:
                        remote_vals[dep] = _read_block(
                            chan_readers[dep], stopped)
                    return remote_vals[dep]

                args = [_resolve(s) for s in arg_specs]
                kwargs = {k: _resolve(s) for k, s in kwarg_specs.items()}
                err = next((a for a in args if isinstance(a, _DagErr)),
                           None) \
                    or next((v for v in kwargs.values()
                             if isinstance(v, _DagErr)), None)
                if err is not None:
                    result: Any = err  # skip execution, propagate fault
                else:
                    try:
                        if method_name == "__dag_fn__":
                            result = instance._fn(*args, **kwargs)
                        else:
                            result = getattr(instance, method_name)(
                                *args, **kwargs)
                    except BaseException as e:  # noqa: BLE001
                        result = _mk_err(method_name, e)
                local[pos] = result
                if writer is not None:
                    _write_block(writer, result, stopped, method_name)
    except _LoopStop:
        return "stopped"


class _FnExecutorHolder:
    """Instance living inside the dedicated actor a FunctionNode compiles
    into; the exec loop calls ``instance._fn``."""

    def __init__(self, fn_bytes: bytes):
        import cloudpickle

        self._fn = cloudpickle.loads(fn_bytes)


class _Slot:
    """Where one argument of a compiled node comes from."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: Any):
        self.kind = kind  # "const" | "node" | "input"
        self.value = value  # constant | node index | None


class CompiledDAGRef:
    """Result handle for one ``execute()`` (reference:
    compiled_dag_ref.py CompiledDAGRef): ``.get()`` — or ``ray_tpu.get``
    — blocks on the DAG's output channels."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._consumed = False
        self._err: Optional[BaseException] = None

    def get(self, timeout: Optional[float] = None):
        # once-only, like the reference: the channel value is consumed
        # by the first get — a second would silently read a LATER
        # execution's output. An execution that raised is re-raised on
        # every get (the channel slot is already consumed; looping on it
        # would wait forever and steal later executions' outputs).
        if self._consumed:
            if self._err is not None:
                raise self._err
            raise ValueError(
                "CompiledDAGRef.get() can only be called once")
        # a timeout below leaves the ref unconsumed: _fetch_result only
        # pops the cache once every output channel has delivered
        vals = self._dag._fetch_result(self._idx, timeout)
        self._consumed = True
        out = []
        for v in vals:
            if isinstance(v, _DagErr):
                self._err = pickle.loads(v.data)
                raise self._err
            out.append(v)
        return out if self._dag._multi else out[0]


class CompiledDAG:
    """Host-side compiled schedule for a DAG (reference:
    compiled_dag_node.py:813)."""

    _READY_TIMEOUT_S = 120.0  # actor start can take seconds on small hosts
    _DEFAULT_BUFFER_BYTES = 4 << 20  # per-channel slot (reference:
    # compiled_dag_node.py _default_buffer_size_bytes)

    def __init__(self, root, buffer_size_bytes: Optional[int] = None,
                 **_kwargs):
        self._buffer_bytes = buffer_size_bytes or self._DEFAULT_BUFFER_BYTES
        self._outputs: List[DAGNode] = list(root) if isinstance(root, list) else [root]
        self._multi = isinstance(root, list)
        self._nodes: List[DAGNode] = []
        self._index: Dict[int, int] = {}  # id(node) -> schedule position
        self._slots: List[Tuple[List[_Slot], Dict[str, _Slot]]] = []
        self._handles: Dict[int, Any] = {}  # schedule pos of ClassNode -> actor
        self._fn_actors: Dict[int, Any] = {}  # pos of FunctionNode -> actor
        self._torn_down = False
        for out in self._outputs:
            self._visit(out)
        self._compile()
        # channel steady state (may be unavailable -> task-path fallback)
        self._channel_mode = False
        self._write_lock = threading.Lock()
        self._read_lock = threading.Lock()
        self._exec_count = 0
        self._read_cursor = 0
        self._result_cache: Dict[int, Any] = {}
        self._partial: List[Any] = []  # outputs read so far this cursor
        try:
            self._compile_channels()
            self._channel_mode = True
        except Exception as e:  # noqa: BLE001 — fall back to task path
            import logging

            import ray_tpu

            logging.getLogger(__name__).info(
                "compiled DAG falls back to task path: %s", e)
            # exec loops may already be running inside the DAG's actors
            # (e.g. one actor attached its channels, another could not):
            # a _STOP through the input channel releases them — otherwise
            # they'd occupy the actor's execution thread forever and the
            # task-path fallback would hang behind them
            sc = getattr(self, "_stop_channel", None)
            if sc is not None:
                try:
                    sc.write(b"stop", timeout=1.0)
                except Exception:  # noqa: BLE001
                    pass
            ic = getattr(self, "_input_channel", None)
            if ic is not None:
                try:
                    ic.write(_STOP, timeout=2.0)
                except Exception:  # noqa: BLE001
                    pass
            self._close_channels()
            for h in self._fn_actors.values():
                try:
                    ray_tpu.kill(h)
                except Exception:  # noqa: BLE001
                    pass
            self._fn_actors.clear()

    # -- compile --------------------------------------------------------
    def _visit(self, node: DAGNode) -> int:
        if id(node) in self._index:
            return self._index[id(node)]
        if isinstance(node, ClassMethodNode):
            self._visit(node._class_node)
        for v in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                self._visit(v)
        pos = len(self._nodes)
        self._index[id(node)] = pos
        self._nodes.append(node)
        return pos

    def _slot(self, v) -> _Slot:
        if isinstance(v, InputNode):
            return _Slot("input", None)
        if isinstance(v, DAGNode):
            return _Slot("node", self._index[id(v)])
        return _Slot("const", v)

    def _compile(self) -> None:
        n_inputs = sum(1 for n in self._nodes if isinstance(n, InputNode))
        if n_inputs > 1:
            raise ValueError("compiled DAGs support at most one InputNode")
        for node in self._nodes:
            args = [self._slot(a) for a in node._bound_args]
            kwargs = {k: self._slot(v) for k, v in node._bound_kwargs.items()}
            self._slots.append((args, kwargs))
            if isinstance(node, ClassNode):
                # actors are part of the compiled graph: created once here
                pos = self._index[id(node)]
                cargs = [s.value for s in args]
                if any(s.kind != "const" for s in args) or any(
                    s.kind != "const" for s in kwargs.values()
                ):
                    raise ValueError(
                        "compiled ClassNode constructor args must be constants"
                    )
                self._handles[pos] = node._actor_cls._remote(
                    tuple(cargs), {k: s.value for k, s in kwargs.items()},
                    node._options,
                )

    # -- channel steady state ------------------------------------------
    def _owner_key(self, pos: int):
        """Which persistent process executes node `pos` (actor id hex)."""
        node = self._nodes[pos]
        if isinstance(node, ClassMethodNode):
            h = self._handles[self._index[id(node._class_node)]]
        elif isinstance(node, ActorMethodNode):
            h = node._handle
        elif isinstance(node, FunctionNode):
            h = self._fn_actors[pos]
        else:
            return None
        return h._actor_id.hex()

    def _compile_channels(self) -> None:
        import ray_tpu

        compute = [pos for pos, n in enumerate(self._nodes)
                   if not isinstance(n, (InputNode, ClassNode))]
        if not compute:
            raise ValueError("no compute nodes to compile")

        # dedicated executor actor per FunctionNode: every compute node
        # must live in a persistent process for the loop to run in
        import cloudpickle

        for pos in compute:
            node = self._nodes[pos]
            if isinstance(node, FunctionNode):
                opts = {k: v for k, v in (node._options or {}).items()
                        if k in ("num_cpus", "num_tpus", "resources",
                                 "scheduling_strategy")}
                self._fn_actors[pos] = ray_tpu.remote(
                    _FnExecutorHolder).options(**opts).remote(
                    cloudpickle.dumps(node._remote_fn._function))

        handle_of: Dict[str, Any] = {}
        owner: Dict[int, str] = {}
        for pos in compute:
            key = self._owner_key(pos)
            owner[pos] = key
            node = self._nodes[pos]
            if isinstance(node, ClassMethodNode):
                handle_of[key] = self._handles[
                    self._index[id(node._class_node)]]
            elif isinstance(node, ActorMethodNode):
                handle_of[key] = node._handle
            else:
                handle_of[key] = self._fn_actors[pos]
        schedule_keys = list(dict.fromkeys(owner[p] for p in compute))

        # channels: one per node consumed across actors or by the driver
        out_positions = [self._index[id(o)] for o in self._outputs]
        consumers: Dict[int, List[str]] = {}
        for pos in compute:
            for s in self._slots[pos][0] + list(self._slots[pos][1].values()):
                if s.kind == "node":
                    dep = s.value
                    if owner.get(dep) is not None and owner[dep] != owner[pos]:
                        lst = consumers.setdefault(dep, [])
                        if owner[pos] not in lst:
                            lst.append(owner[pos])
        self._edge_channels: Dict[int, Channel] = {}
        reader_idx: Dict[Tuple[int, str], int] = {}
        self._out_readers: List[Any] = []
        for dep in set(list(consumers) + out_positions):
            keys = consumers.get(dep, [])
            n_readers = len(keys) + (1 if dep in out_positions else 0)
            ch = Channel(capacity=self._buffer_bytes, num_readers=n_readers)
            self._edge_channels[dep] = ch
            for i, k in enumerate(keys):
                reader_idx[(dep, k)] = i
        for dep in out_positions:
            ch = self._edge_channels[dep]
            self._out_readers.append(
                ch.reader(ch.num_readers - 1))

        # ONE input channel read by every schedule: it is the iteration
        # trigger even for schedules whose nodes take no input
        self._input_channel = Channel(capacity=self._buffer_bytes,
                                      num_readers=len(schedule_keys))
        # never read by anyone: a teardown write advances its header seq,
        # which every exec-loop blocking tick polls as the stop signal
        self._stop_channel = Channel(capacity=64,
                                     num_readers=len(schedule_keys))

        # build + ship per-actor schedules
        self._ready_readers = []
        self._ready_channels = []  # keep writer endpoints alive: their
        # GC would unlink the shm segment before the actor attaches
        self._loop_refs = []
        for si, key in enumerate(schedule_keys):
            steps = []
            chan_readers: Dict[int, Any] = {}
            for pos in compute:
                if owner[pos] != key:
                    continue
                node = self._nodes[pos]
                if isinstance(node, (ClassMethodNode, ActorMethodNode)):
                    method = node._method_name
                else:
                    method = "__dag_fn__"

                def spec_of(s: _Slot):
                    if s.kind == "const":
                        return ("c", s.value)
                    if s.kind == "input":
                        return ("i",)
                    dep = s.value
                    if isinstance(self._nodes[dep], InputNode):
                        return ("i",)
                    if isinstance(self._nodes[dep], ClassNode):
                        raise ValueError(
                            "actor handles cannot flow through channels")
                    if owner[dep] == key:
                        return ("l", dep)
                    if dep not in chan_readers:
                        ch = self._edge_channels[dep]
                        chan_readers[dep] = ch.reader(
                            reader_idx[(dep, key)])
                    return ("r", dep)

                arg_specs = [spec_of(s) for s in self._slots[pos][0]]
                kwarg_specs = {k: spec_of(s)
                               for k, s in self._slots[pos][1].items()}
                steps.append((pos, method, arg_specs, kwarg_specs,
                              self._edge_channels.get(pos)))
            ready = Channel(num_readers=1)
            self._ready_channels.append(ready)
            self._ready_readers.append(ready.reader(0))
            self._loop_refs.append(
                handle_of[key].__ray_call__.remote(
                    _dag_exec_loop, ready, self._input_channel.reader(si),
                    steps, chan_readers, self._stop_channel.reader(si)))
        # handshake: every exec loop attached its channels and is serving
        deadline = time.monotonic() + self._READY_TIMEOUT_S
        for rd in self._ready_readers:
            left = max(1.0, deadline - time.monotonic())
            if rd.read(timeout=left) != "ready":
                raise RuntimeError("exec loop handshake failed")

    def _close_channels(self) -> None:
        for ch in list(getattr(self, "_edge_channels", {}).values()):
            ch.close()
        for ch in list(getattr(self, "_ready_channels", [])):
            ch.close()
        ic = getattr(self, "_input_channel", None)
        if ic is not None:
            ic.close()
        sc = getattr(self, "_stop_channel", None)
        if sc is not None:
            sc.close()
        self._edge_channels = {}
        self._ready_channels = []
        self._input_channel = None
        self._stop_channel = None

    # -- execute --------------------------------------------------------
    def execute(self, *input_values):
        if self._torn_down:
            raise RuntimeError("CompiledDAG was torn down")
        input_value = input_values[0] if input_values else None
        if self._channel_mode:
            with self._write_lock:
                # the write backpressures on channel acks: the driver can
                # pipeline at most one value ahead per channel slot. Tick
                # so a dead exec loop (stopped acking) surfaces as an
                # error instead of wedging the writer — and teardown
                # (which sets _torn_down) can reclaim the lock. The index
                # is claimed only AFTER the write succeeds: a failed
                # write (e.g. oversized input) must not desynchronize
                # CompiledDAGRef indices from the read cursor.
                import ray_tpu

                while True:
                    if self._torn_down:
                        raise RuntimeError("CompiledDAG was torn down")
                    try:
                        self._input_channel.write(input_value, timeout=2.0)
                        break
                    except ChannelTimeoutError:
                        done, _ = ray_tpu.wait(self._loop_refs,
                                               num_returns=1, timeout=0)
                        if done:
                            ray_tpu.get(done[0])
                            raise RuntimeError(
                                "a compiled-DAG exec loop exited"
                            ) from None
                idx = self._exec_count
                self._exec_count += 1
            return CompiledDAGRef(self, idx)
        return self._execute_taskpath(input_value)

    def _read_output(self, rd, timeout: Optional[float]):
        """One output-channel read in short ticks, detecting a dead exec
        loop (its __ray_call__ ref resolves early) instead of hanging."""
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return rd.read(timeout=2.0)
            except ChannelTimeoutError:
                if self._torn_down:
                    raise RuntimeError("CompiledDAG was torn down") from None
                done, _ = ray_tpu.wait(self._loop_refs,
                                       num_returns=1, timeout=0)
                if done:
                    # surfaces the loop's error (e.g. its actor died)
                    ray_tpu.get(done[0])
                    raise RuntimeError(
                        "a compiled-DAG exec loop exited") from None
                if deadline is not None and time.monotonic() > deadline:
                    from ray_tpu.exceptions import GetTimeoutError

                    raise GetTimeoutError(
                        f"compiled DAG output not ready within "
                        f"{timeout}s") from None

    def _fetch_result(self, idx: int, timeout: Optional[float]):
        """Pop execution ``idx``'s raw output list (``_DagErr`` entries
        included — CompiledDAGRef.get unwraps them so it can record the
        consumption before raising)."""
        with self._read_lock:
            while idx not in self._result_cache:
                if self._torn_down:
                    raise RuntimeError("CompiledDAG was torn down")
                # one output at a time, stashing partial progress: a
                # timeout after output A was consumed but before slow
                # output B must NOT discard A — the retry would pair
                # A's next execution with B's current one, shifting
                # every later result
                while len(self._partial) < len(self._out_readers):
                    rd = self._out_readers[len(self._partial)]
                    self._partial.append(self._read_output(rd, timeout))
                vals, self._partial = self._partial, []
                self._result_cache[self._read_cursor] = vals
                self._read_cursor += 1
            return self._result_cache.pop(idx)

    def _execute_taskpath(self, input_value):
        """Fallback: per-execute task submission (pre-channel behavior)."""
        results: List[Any] = [None] * len(self._nodes)

        def resolve(slot: _Slot):
            if slot.kind == "const":
                return slot.value
            if slot.kind == "input":
                return input_value
            return results[slot.value]

        for pos, node in enumerate(self._nodes):
            arg_slots, kwarg_slots = self._slots[pos]
            if isinstance(node, InputNode):
                results[pos] = input_value
                continue
            if isinstance(node, ClassNode):
                results[pos] = self._handles[pos]
                continue
            args = tuple(resolve(s) for s in arg_slots)
            kwargs = {k: resolve(s) for k, s in kwarg_slots.items()}
            if isinstance(node, FunctionNode):
                results[pos] = node._remote_fn._remote(args, kwargs, node._options)
            elif isinstance(node, ClassMethodNode):
                handle = self._handles[self._index[id(node._class_node)]]
                results[pos] = handle._actor_method_call(node._method_name, args, kwargs)
            elif isinstance(node, ActorMethodNode):
                results[pos] = node._handle._actor_method_call(
                    node._method_name, args, kwargs
                )
            else:
                raise TypeError(f"cannot compile node type {type(node).__name__}")
        outs = [results[self._index[id(o)]] for o in self._outputs]
        return outs if self._multi else outs[0]

    def teardown(self) -> None:
        """Stop exec loops and kill actors this compiled DAG created
        (reference: CompiledDAG.teardown). Ordering matters for actors
        the DAG did NOT create (ActorMethodNode handles, which stay
        alive for their owner): their loops must see _STOP, which needs
        (a) output channels drained so blocked writers progress, and
        (b) the input channel free of a wedged concurrent execute() —
        _torn_down makes that writer bail within one tick."""
        import ray_tpu

        if self._torn_down:
            return
        self._torn_down = True
        if self._channel_mode:
            # stop signal FIRST: every exec-loop blocking tick polls this
            # channel's header, so even a loop wedged on a dead
            # upstream's edge exits within one tick
            try:
                self._stop_channel.write(b"stop", timeout=1.0)
            except Exception:  # noqa: BLE001
                pass
            # let a blocked execute()/get() observe _torn_down and exit
            got_write = self._write_lock.acquire(timeout=10.0)
            got_read = self._read_lock.acquire(timeout=10.0)
            try:
                # drain unread outputs so exec loops blocked writing a
                # full output slot can reach their input read
                for rd in self._out_readers:
                    while True:
                        try:
                            rd.read(timeout=0.2)
                        except ChannelTimeoutError:
                            break
                try:
                    # unblocks every schedule's input read; loops exit
                    self._input_channel.write(_STOP, timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass
            finally:
                if got_read:
                    self._read_lock.release()
                if got_write:
                    self._write_lock.release()
        for handle in list(self._handles.values()) + list(
                self._fn_actors.values()):
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        self._handles.clear()
        self._fn_actors.clear()
        self._close_channels()

    def __del__(self):
        try:
            if not self._torn_down and (self._fn_actors or self._channel_mode):
                self.teardown()
        except Exception:  # noqa: BLE001
            pass
