"""ray_tpu.dashboard — cluster dashboard + job submission server.

Reference: python/ray/dashboard (DashboardHead head.py:49, job module).
HTTP API over GCS state plus a subprocess-based JobManager;
JobSubmissionClient mirrors ray.job_submission.JobSubmissionClient.
"""

from ray_tpu.dashboard.head import DashboardHead
from ray_tpu.dashboard.job_client import JobSubmissionClient
from ray_tpu.dashboard.job_manager import JobManager

__all__ = ["DashboardHead", "JobManager", "JobSubmissionClient"]
