"""Per-node dashboard agent.

Reference: python/ray/dashboard/agent.py:35 — every node runs a small
agent so the head can fetch that node's logs, process stats, and
health WITHOUT funneling bulk data through the GCS (the r4 verdict's
gap: "logs/metrics from remote nodes still funnel through GCS").

The agent is an asyncio HTTP server colocated with the raylet (same
process, same event loop — one fewer daemon per node than the
reference, which is the right trade at TPU-host process counts):

    GET /api/local/health          {"ok": true, "node_id": ...}
    GET /api/local/stats           psutil cpu/mem + worker count
    GET /api/local/logs            list of log files in the session dir
    GET /api/local/logs/<name>     tail of one log file (?lines=N)
    GET /api/local/raylet          the raylet's GetState dict

The head proxies ``/api/nodes/<node_id>/...`` to the owning node's
agent (head.py), using the agent address each raylet registers with
the GCS.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Any, Optional, Tuple
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)

_MAX_TAIL_BYTES = 1 << 20


def _json_bytes(obj) -> bytes:
    return json.dumps(obj, default=str).encode()


class NodeAgent:
    """HTTP endpoint for one node's local observability, plus a reporter
    loop shipping periodic samples to the head (reference:
    dashboard/agent.py's reporter module — the head reads fresh per-node
    stats without a fan-out poll at query time)."""

    REPORT_PERIOD_S = 2.0

    def __init__(self, raylet, host: str = "127.0.0.1", port: int = 0):
        self.raylet = raylet
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._reporter_task: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._reporter_task = asyncio.ensure_future(self._reporter_loop())
        logger.info("node agent on :%d", self.port)
        return self.host, self.port

    async def _reporter_loop(self) -> None:
        """Push this node's stats to the GCS aggregator; the head serves
        them from /api/v0/node_stats (and `rstate.list_node_stats()`)."""
        while not self._closed:
            await asyncio.sleep(self.REPORT_PERIOD_S)
            gcs = getattr(self.raylet, "gcs", None)
            if gcs is None:
                continue
            try:
                await gcs.acall(
                    "ReportNodeStats", node_id=self.raylet.node_id,
                    stats=self._stats(), timeout=10)
            except Exception:  # noqa: BLE001 — reporting is best-effort
                pass

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request.decode("latin1").split()
            if len(parts) < 2:
                return
            method, target = parts[0], parts[1]
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
            status, body = await self._dispatch(method, target)
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close"
                f"\r\n\r\n".encode() + body)
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    async def _dispatch(self, method: str, target: str):
        url = urlparse(target)
        path = url.path.rstrip("/")
        if method != "GET":
            return "405 Method Not Allowed", _json_bytes(
                {"error": "GET only"})
        if path == "/api/local/health":
            return "200 OK", _json_bytes(
                {"ok": True, "node_id": self.raylet.node_id})
        if path == "/api/local/stats":
            return "200 OK", _json_bytes(self._stats())
        if path == "/api/local/raylet":
            return "200 OK", _json_bytes(await self.raylet.GetState())
        if path == "/api/local/logs":
            return "200 OK", _json_bytes(self._log_index())
        if path.startswith("/api/local/logs/"):
            name = path[len("/api/local/logs/"):]
            qs = parse_qs(url.query)
            lines = int(qs.get("lines", ["200"])[0])
            return self._log_tail(name, lines)
        return "404 Not Found", _json_bytes({"error": f"no route {path}"})

    def _stats(self) -> dict:
        import psutil

        vm = psutil.virtual_memory()
        out = {
            "node_id": self.raylet.node_id,
            "cpu_percent": psutil.cpu_percent(interval=None),
            "mem_total": vm.total,
            "mem_available": vm.available,
            "num_workers": len(self.raylet.workers),
            "num_leases": len(self.raylet.leases),
            "num_oom_kills": self.raylet.num_oom_kills,
        }
        # object-store fill (the store daemon's own accounting)
        store = getattr(self.raylet, "store", None)
        if store is not None:
            try:
                m = store.metrics()
                out["store_capacity"] = m.get("capacity", 0)
                out["store_allocated"] = m.get("allocated", 0)
                out["store_num_objects"] = m.get("num_objects", 0)
            except Exception:  # noqa: BLE001 — store busy/restarting
                pass
        return out

    def _log_index(self) -> dict:
        d = self.raylet.session_dir
        out = []
        try:
            for name in sorted(os.listdir(d)):
                full = os.path.join(d, name)
                if name.endswith(".log") and os.path.isfile(full):
                    out.append({"name": name,
                                "size": os.path.getsize(full)})
        except OSError:
            pass
        return {"logs": out}

    def _log_tail(self, name: str, lines: int):
        # the session dir is the ONLY readable root (no traversal)
        if "/" in name or ".." in name or not name.endswith(".log"):
            return "400 Bad Request", _json_bytes(
                {"error": "bad log name"})
        full = os.path.join(self.raylet.session_dir, name)
        try:
            size = os.path.getsize(full)
            with open(full, "rb") as f:
                f.seek(max(0, size - _MAX_TAIL_BYTES))
                text = f.read().decode("utf-8", "replace")
        except OSError as e:
            return "404 Not Found", _json_bytes({"error": str(e)})
        tail = text.splitlines()[-max(1, lines):]
        return "200 OK", _json_bytes({"name": name, "lines": tail})

    def close(self) -> None:
        self._closed = True
        if self._reporter_task is not None:
            self._reporter_task.cancel()
        if self._server is not None:
            self._server.close()
