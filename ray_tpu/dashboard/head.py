"""Dashboard head — HTTP API over cluster state + job submission.

Reference: python/ray/dashboard/head.py:49 (DashboardHead) and
dashboard/modules/job/job_head.py (the REST routes). Dependency-free
asyncio HTTP server (same pattern as serve/http_proxy.py) running in a
background thread; reads state from the GCS, owns a JobManager.

Routes:
    GET  /                     minimal HTML overview
    GET  /api/version          {"version": ...}
    GET  /api/nodes            node table
    GET  /api/actors           actor table
    GET  /api/jobs/            submission records (+ driver jobs)
    POST /api/jobs/            {"entrypoint": ..., "runtime_env": {...}}
    GET  /api/jobs/<id>        one submission record
    POST /api/jobs/<id>/stop   terminate the job subprocess
    GET  /api/jobs/<id>/logs   {"logs": "..."}
    GET  /api/tasks            recent task events
    GET  /api/cluster_status   resources + demand summary
    GET  /api/v0/events        cluster event bus (observability/)
    GET  /api/v0/traces/<job>  a job's span tree (distributed tracing)
    GET  /api/v0/node_stats    per-node reporter samples
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, Optional, Tuple

from ray_tpu._version import version as __version__
from ray_tpu.dashboard.job_manager import JobManager


def _json_bytes(obj) -> bytes:
    return json.dumps(obj, default=str).encode()


class DashboardHead:
    def __init__(self, gcs_addr: Tuple[str, int], host: str = "127.0.0.1",
                 port: int = 8265, log_dir: Optional[str] = None):
        self.gcs_addr = tuple(gcs_addr)
        self.host = host
        self.port = port
        self.job_manager = JobManager(self.gcs_addr, log_dir=log_dir)
        self._gcs_client = None  # one persistent connection (thread-safe)
        self._loop = asyncio.new_event_loop()
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ray-tpu-dashboard")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("dashboard failed to start")

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start())
        self._started.set()
        self._loop.run_forever()

    async def _start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    def _gcs(self):
        if self._gcs_client is None:
            from ray_tpu._private.rpc import RpcClient

            self._gcs_client = RpcClient(*self.gcs_addr)
        return self._gcs_client

    # -- HTTP plumbing -------------------------------------------------
    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            line = await reader.readline()
            if not line:
                return
            parts = line.decode().split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            clen = 0
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":", 1)[1])
            body = await reader.readexactly(clen) if clen else b""
            status, ctype, payload = await asyncio.get_event_loop()\
                .run_in_executor(None, self._dispatch, method, path, body)
            writer.write(
                f"HTTP/1.1 {status} {'OK' if status < 400 else 'ERR'}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n".encode() + payload)
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:  # noqa: BLE001
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    # -- routing (runs in executor thread; RPC calls block) ------------
    def _dispatch(self, method: str, path: str,
                  body: bytes) -> Tuple[int, str, bytes]:
        try:
            path = path.split("?", 1)[0].rstrip("/") or "/"
            if method == "GET" and path == "/":
                return 200, "text/html", self._html().encode()
            if method == "GET" and path == "/api/version":
                return 200, "application/json", _json_bytes(
                    {"version": __version__})
            if method == "GET" and path == "/api/nodes":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("GetAllNodeInfo", timeout=10))
            if method == "GET" and path == "/api/actors":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("ListActors", timeout=10))
            if method == "GET" and path == "/api/tasks":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("ListTaskEvents", limit=1000,
                                     timeout=10))
            if method == "GET" and path == "/api/cluster_status":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("GetClusterDemand", timeout=10))
            # observability subsystem (event bus + traces + node stats)
            if method == "GET" and path == "/api/v0/events":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("ListClusterEvents", limit=2000,
                                     timeout=10))
            if method == "GET" and path == "/api/v0/node_stats":
                return 200, "application/json", _json_bytes(
                    self._gcs().call("ListNodeStats", timeout=10))
            if method == "GET" and path.startswith("/api/v0/traces/"):
                job_id = path[len("/api/v0/traces/"):]
                return 200, "application/json", _json_bytes(
                    self._gcs().call("GetTrace", job_id=job_id,
                                     timeout=10))
            if path == "/api/jobs":
                if method == "GET":
                    return 200, "application/json", _json_bytes(
                        self.job_manager.list_jobs())
                if method == "POST":
                    req = json.loads(body or b"{}")
                    sid = self.job_manager.submit_job(
                        entrypoint=req["entrypoint"],
                        submission_id=req.get("submission_id"),
                        runtime_env=req.get("runtime_env"),
                        metadata=req.get("metadata"),
                    )
                    return 200, "application/json", _json_bytes(
                        {"submission_id": sid})
            if method == "GET" and path.startswith("/api/nodes/"):
                # proxy to the owning node's agent (reference:
                # dashboard/agent.py — per-node logs/stats without
                # funneling bulk data through the GCS)
                rest = path[len("/api/nodes/"):]
                node_id, _, agent_path = rest.partition("/")
                return self._proxy_agent(node_id, agent_path)
            if path.startswith("/api/jobs/"):
                rest = path[len("/api/jobs/"):]
                if rest.endswith("/logs") and method == "GET":
                    sid = rest[: -len("/logs")]
                    return 200, "application/json", _json_bytes(
                        {"logs": self.job_manager.get_job_logs(sid)})
                if rest.endswith("/stop") and method == "POST":
                    sid = rest[: -len("/stop")]
                    return 200, "application/json", _json_bytes(
                        {"stopped": self.job_manager.stop_job(sid)})
                if method == "GET":
                    info = self.job_manager.get_job_info(rest)
                    if info is None:
                        return 404, "application/json", _json_bytes(
                            {"error": f"no job {rest!r}"})
                    return 200, "application/json", _json_bytes(info)
            return 404, "application/json", _json_bytes(
                {"error": f"no route {method} {path}"})
        except Exception as e:  # noqa: BLE001
            return 500, "application/json", _json_bytes({"error": str(e)})

    def _proxy_agent(self, node_id: str,
                     agent_path: str) -> Tuple[int, str, bytes]:
        nodes = self._gcs().call("GetAllNodeInfo", timeout=10) or []
        node = next((n for n in nodes if n["NodeID"] == node_id
                     or n["NodeID"].startswith(node_id)), None)
        if node is None:
            return 404, "application/json", _json_bytes(
                {"error": f"no node {node_id!r}"})
        port = node.get("AgentPort") or 0
        if not port:
            return 502, "application/json", _json_bytes(
                {"error": "node has no agent"})
        import urllib.request

        url = (f"http://{node['NodeManagerAddress']}:{port}"
               f"/api/local/{agent_path}")
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status, "application/json", resp.read()
        except Exception as e:  # noqa: BLE001
            return 502, "application/json", _json_bytes(
                {"error": f"agent unreachable: {e}"})

    def _html(self) -> str:
        from html import escape as esc

        gcs = self._gcs()
        nodes = gcs.call("GetAllNodeInfo", timeout=10) or []
        actors = gcs.call("ListActors", timeout=10) or []
        jobs = self.job_manager.list_jobs()
        rows = "".join(
            f"<tr><td>{esc(n['NodeID'][:12])}</td><td>{'head' if n.get('IsHead') else 'worker'}"
            f"</td><td>{'alive' if n.get('Alive') else 'dead'}</td>"
            f"<td>{esc(str(n.get('Resources')))}</td>"
            f"<td>{esc(str(n.get('AvailableResources')))}</td></tr>"
            for n in nodes)
        arows = "".join(
            f"<tr><td>{esc(a['actor_id'][:12])}</td><td>{esc(a.get('name') or '')}"
            f"</td><td>{esc(a['state'])}</td></tr>" for a in actors)
        jrows = "".join(
            f"<tr><td>{esc(j['submission_id'])}</td><td>{esc(j['status'])}</td>"
            f"<td><code>{esc(j['entrypoint'][:60])}</code></td></tr>"
            for j in jobs)
        return (
            "<html><head><title>ray_tpu dashboard</title><style>"
            "body{font-family:sans-serif;margin:2em}table{border-collapse:"
            "collapse}td,th{border:1px solid #ccc;padding:4px 8px}</style>"
            f"</head><body><h1>ray_tpu {__version__}</h1>"
            f"<h2>Nodes ({len(nodes)})</h2><table><tr><th>id</th><th>role"
            f"</th><th>state</th><th>total</th><th>available</th></tr>"
            f"{rows}</table>"
            f"<h2>Actors ({len(actors)})</h2><table><tr><th>id</th>"
            f"<th>name</th><th>state</th></tr>{arows}</table>"
            f"<h2>Jobs ({len(jobs)})</h2><table><tr><th>id</th><th>status"
            f"</th><th>entrypoint</th></tr>{jrows}</table>"
            "</body></html>")

    def shutdown(self) -> None:
        self.job_manager.shutdown()

        def _close():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_close)
            self._thread.join(timeout=5)
        except Exception:  # noqa: BLE001
            pass


def main(argv=None) -> int:
    """Daemon entry: `python -m ray_tpu.dashboard.head --gcs-addr h:p`
    (spawned by `ray-tpu start --head`)."""
    import argparse
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs-addr", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8265)
    a = ap.parse_args(argv)
    h, p = a.gcs_addr.rsplit(":", 1)
    head = DashboardHead((h, int(p)), host=a.host, port=a.port)
    print(f"dashboard at {head.address}", flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    raise SystemExit(main())
