"""JobSubmissionClient — HTTP client for the job-submission API.

Reference surface: python/ray/dashboard/modules/job/sdk.py:37
(`JobSubmissionClient`). stdlib urllib, no dependencies.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


class JobSubmissionClient:
    def __init__(self, address: str):
        """address: the dashboard URL, e.g. "http://127.0.0.1:8265"."""
        self.address = address.rstrip("/")
        if not self.address.startswith("http"):
            self.address = "http://" + self.address

    def _request(self, method: str, path: str,
                 payload: Optional[dict] = None) -> Any:
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self.address + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            try:
                msg = json.loads(body).get("error", body)
            except Exception:  # noqa: BLE001
                msg = body
            raise RuntimeError(f"{method} {path}: {msg}") from None

    # -- API (reference: sdk.py) --------------------------------------
    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        reply = self._request("POST", "/api/jobs/", {
            "entrypoint": entrypoint,
            "submission_id": submission_id,
            "runtime_env": runtime_env,
            "metadata": metadata,
        })
        return reply["submission_id"]

    def list_jobs(self) -> List[Dict]:
        return self._request("GET", "/api/jobs/")

    def get_job_info(self, submission_id: str) -> Dict:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        return self._request(
            "GET", f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request(
            "POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def tail_job_logs(self, submission_id: str, poll_s: float = 0.5):
        """Generator of new log text until the job reaches a terminal
        state (reference: sdk.py tail_job_logs, sync flavor)."""
        seen = 0
        while True:
            logs = self.get_job_logs(submission_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            if self.get_job_status(submission_id) in (
                    "SUCCEEDED", "FAILED", "STOPPED"):
                rest = self.get_job_logs(submission_id)
                if len(rest) > seen:
                    yield rest[seen:]
                return
            time.sleep(poll_s)
