"""Job manager — driver-script lifecycle behind the job-submission API.

Reference: python/ray/dashboard/modules/job/job_manager.py. A submitted
job is a subprocess running the entrypoint with RAY_TPU_ADDRESS exported
(the script's ray_tpu.init() connects to the cluster); stdout/stderr go
to a per-job log file; a monitor thread tracks PENDING → RUNNING →
SUCCEEDED/FAILED/STOPPED. Submission records persist in the GCS KV
(namespace "job_submissions") so `list_jobs` survives a dashboard
restart — the reference stores them in the GCS internal KV the same way.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

JOB_KV_NAMESPACE = "job_submissions"


class JobManager:
    def __init__(self, gcs_addr: Tuple[str, int],
                 log_dir: Optional[str] = None):
        from ray_tpu._private.rpc import RpcClient

        self.gcs_addr = tuple(gcs_addr)
        self.gcs = RpcClient(*self.gcs_addr)
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="ray_tpu_jobs_")
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stopping: set = set()  # sids being stopped (monitor race)
        self._lock = threading.Lock()

    # -- KV-backed records --------------------------------------------
    def _put_record(self, rec: dict) -> None:
        self.gcs.call(
            "KVPut", ns=JOB_KV_NAMESPACE,
            key=rec["submission_id"],
            value=json.dumps(rec).encode(), overwrite=True, timeout=10)

    def _get_record(self, submission_id: str) -> Optional[dict]:
        v = self.gcs.call("KVGet", ns=JOB_KV_NAMESPACE,
                          key=submission_id, timeout=10)
        return json.loads(v) if v else None

    def list_jobs(self) -> List[dict]:
        keys = self.gcs.call("KVKeys", ns=JOB_KV_NAMESPACE,
                             prefix="", timeout=10) or []
        out = []
        for k in keys:
            rec = self._get_record(k if isinstance(k, str) else k.decode())
            if rec:
                out.append(rec)
        return sorted(out, key=lambda r: r.get("start_time") or 0)

    # -- lifecycle -----------------------------------------------------
    def submit_job(
        self,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if self._get_record(submission_id):
            raise ValueError(f"job {submission_id!r} already exists")
        runtime_env = runtime_env or {}
        log_path = os.path.join(self.log_dir, f"{submission_id}.log")
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"
        env.update({str(k): str(v)
                    for k, v in (runtime_env.get("env_vars") or {}).items()})
        cwd = runtime_env.get("working_dir") or None
        rec = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": "PENDING",
            "start_time": time.time(),
            "end_time": None,
            "metadata": metadata or {},
            "log_path": log_path,
            "message": "",
        }
        self._put_record(rec)
        try:
            logf = open(log_path, "ab")
            proc = subprocess.Popen(
                ["bash", "-c", entrypoint], env=env, cwd=cwd,
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,  # own process group for stop_job
            )
        except Exception as e:  # noqa: BLE001
            rec.update(status="FAILED", end_time=time.time(),
                       message=f"failed to start: {e}")
            self._put_record(rec)
            return submission_id
        with self._lock:
            self._procs[submission_id] = proc
        rec["status"] = "RUNNING"
        rec["pid"] = proc.pid  # stop_job fallback after a manager restart
        self._put_record(rec)
        threading.Thread(target=self._monitor, args=(submission_id, proc),
                         daemon=True).start()
        return submission_id

    def _monitor(self, submission_id: str, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        with self._lock:
            # both waits return at process death; the _stopping marker is
            # set BEFORE the signal, so a user-stopped job is never
            # overwritten as FAILED(exit -15) by this thread
            if submission_id in self._stopping:
                return
        rec = self._get_record(submission_id) or {}
        if rec.get("status") == "STOPPED":
            return  # stop_job already wrote the terminal record
        rec.update(
            status="SUCCEEDED" if rc == 0 else "FAILED",
            end_time=time.time(),
            message="" if rc == 0 else f"exit code {rc}",
        )
        self._put_record(rec)
        with self._lock:
            self._procs.pop(submission_id, None)

    def get_job_status(self, submission_id: str) -> Optional[str]:
        rec = self._get_record(submission_id)
        return rec["status"] if rec else None

    def get_job_info(self, submission_id: str) -> Optional[dict]:
        return self._get_record(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        rec = self._get_record(submission_id)
        if not rec:
            raise ValueError(f"no job {submission_id!r}")
        try:
            with open(rec["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        with self._lock:
            proc = self._procs.pop(submission_id, None)
            self._stopping.add(submission_id)
        rec = self._get_record(submission_id)
        pid = proc.pid if proc is not None else (rec or {}).get("pid")
        signaled = False
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except Exception:  # noqa: BLE001
                proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except Exception:  # noqa: BLE001
                    proc.kill()
            signaled = True
        elif proc is None and pid:
            # manager restarted: the record's pid is the only handle to
            # the (session-leading) orphan — signal its process group
            try:
                os.killpg(pid, signal.SIGTERM)
                signaled = True
            except ProcessLookupError:
                pass  # already gone
            except Exception:  # noqa: BLE001
                pass
        if rec and rec.get("status") in ("PENDING", "RUNNING"):
            # mark STOPPED only once the process was signaled or is gone —
            # never report a job stopped while its entrypoint still runs
            gone = True
            if pid:
                try:
                    os.kill(pid, 0)
                    gone = False
                except ProcessLookupError:
                    gone = True
            if signaled or gone:
                rec.update(status="STOPPED", end_time=time.time())
                self._put_record(rec)
        return signaled

    def shutdown(self) -> None:
        with self._lock:
            ids = list(self._procs)
        for sid in ids:
            self.stop_job(sid)
