"""ray_tpu.data — distributed data loading/processing (reference: ray.data).

Lazy block-based datasets over the shared-memory object store; per-block
ops fuse into single tasks; `iter_jax_batches` is the TPU ingest path.
"""

from ray_tpu.data.block import Block
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.read_api import (
    from_arrow,
    from_blocks,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "Block",
    "Dataset",
    "GroupedData",
    "from_arrow",
    "from_blocks",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
