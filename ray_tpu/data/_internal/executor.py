"""Streaming plan executor over ray_tpu tasks.

Reference architecture: python/ray/data/_internal/execution/
streaming_executor.py:100 — operators pull upstream lazily, blocks flow
as ObjectRefs, bounded in-flight tasks give backpressure. This is a
compact equivalent: each logical op maps block-refs → block-refs via
remote tasks with a sliding window (no materialize-the-world stages).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.block import Block

# Max concurrently-running block tasks per op (backpressure window;
# reference: backpressure_policy/concurrency_cap_backpressure_policy.py).
DEFAULT_CONCURRENCY = 16


@ray_tpu.remote
def _apply_block_fn(fn_bytes: bytes, *blocks: Block) -> Any:
    from ray_tpu._private.serialization import loads_function

    fn = loads_function(fn_bytes)
    return fn(*blocks)


def _pack(fn: Callable) -> bytes:
    from ray_tpu._private.serialization import dumps_function

    return dumps_function(fn)


class Executor:
    """Maps block refs through per-block remote tasks with a bounded
    in-flight window, yielding result refs in order as they finish."""

    def __init__(self, concurrency: int = DEFAULT_CONCURRENCY):
        self.concurrency = concurrency

    def map_refs(
        self,
        fn: Callable[[Block], Block],
        refs: Iterator[Any],
        local: bool = False,
    ) -> Iterator[Any]:
        """Lazily apply fn to each block ref. `local=True` short-circuits
        through the driver (tiny plans, local mode)."""
        if local:
            for r in refs:
                blk = ray_tpu.get(r) if hasattr(r, "id") else r
                yield ray_tpu.put(fn(blk))
            return
        fn_b = _pack(fn)
        window: List[Any] = []
        for r in refs:
            window.append(_apply_block_fn.remote(fn_b, r))
            if len(window) >= self.concurrency:
                yield window.pop(0)
        while window:
            yield window.pop(0)
