"""Streaming plan executor over ray_tpu tasks.

Reference architecture: python/ray/data/_internal/execution/
streaming_executor.py:100 — operators pull upstream lazily, blocks flow
as ObjectRefs, bounded in-flight tasks give backpressure. This is a
compact equivalent: each logical op maps block-refs → block-refs via
remote tasks with a sliding window (no materialize-the-world stages).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.block import Block

# Max concurrently-running block tasks per op (backpressure window;
# reference: backpressure_policy/concurrency_cap_backpressure_policy.py).
DEFAULT_CONCURRENCY = 16


@ray_tpu.remote
def _apply_block_fn(fn_bytes: bytes, *blocks: Block) -> Any:
    from ray_tpu._private.serialization import loads_function

    fn = loads_function(fn_bytes)
    return fn(*blocks)


def _pack(fn: Callable) -> bytes:
    from ray_tpu._private.serialization import dumps_function

    return dumps_function(fn)


@ray_tpu.remote
def _partition_block_fn(fn_bytes: bytes, block: Block, k: int, idx: int) -> Any:
    """Map side of a distributed shuffle: fn(block, k, idx) -> k parts."""
    from ray_tpu._private.serialization import loads_function

    parts = loads_function(fn_bytes)(block, k, idx)
    return parts if k > 1 else parts[0]


@ray_tpu.remote
def _reduce_parts_fn(fn_bytes: bytes, *parts: Block) -> Any:
    """Reduce side: fn(list_of_parts) -> one output block."""
    from ray_tpu._private.serialization import loads_function

    return loads_function(fn_bytes)(list(parts))


class Executor:
    """Maps block refs through per-block remote tasks with a bounded
    in-flight window, yielding result refs in order as they finish."""

    def __init__(self, concurrency: int = DEFAULT_CONCURRENCY):
        self.concurrency = concurrency

    def map_refs(
        self,
        fn: Callable[[Block], Block],
        refs: Iterator[Any],
        local: bool = False,
    ) -> Iterator[Any]:
        """Lazily apply fn to each block ref. `local=True` short-circuits
        through the driver (tiny plans, local mode)."""
        if local:
            for r in refs:
                blk = ray_tpu.get(r) if hasattr(r, "id") else r
                yield ray_tpu.put(fn(blk))
            return
        fn_b = _pack(fn)
        window: List[Any] = []
        for r in refs:
            window.append(_apply_block_fn.remote(fn_b, r))
            if len(window) >= self.concurrency:
                yield window.pop(0)
        while window:
            yield window.pop(0)

    def shuffle_refs(
        self,
        refs: List[Any],
        partition_fn: Callable[[Block, int, int], List[Block]],
        reduce_fn: Callable[[List[Block]], Block],
        num_outputs: Optional[int] = None,
        local: bool = False,
    ) -> Iterator[Any]:
        """Two-stage distributed shuffle (reference: map/reduce shuffle in
        _internal/planner/{sort,random_shuffle}.py): each input block is
        partitioned into k parts by a remote map task (which also receives
        its block index — per-block RNG seeds need it); reduce task j
        concatenates part j of every map output. Only REFS pass through
        the driver — blocks never materialize here."""
        refs = list(refs)
        if not refs:
            return
        k = num_outputs if num_outputs is not None else len(refs)
        k = max(1, k)
        if local:
            blocks = [ray_tpu.get(r) if hasattr(r, "id") else r for r in refs]
            parts = [partition_fn(b, k, i) for i, b in enumerate(blocks)]
            for j in range(k):
                yield ray_tpu.put(reduce_fn([p[j] for p in parts]))
            return
        pfn_b = _pack(partition_fn)
        rfn_b = _pack(reduce_fn)
        part_refs: List[List[Any]] = []
        for i, r in enumerate(refs):
            out = _partition_block_fn.options(num_returns=k).remote(pfn_b, r, k, i)
            part_refs.append(out if isinstance(out, list) else [out])
        for j in range(k):
            yield _reduce_parts_fn.remote(rfn_b, *[p[j] for p in part_refs])
