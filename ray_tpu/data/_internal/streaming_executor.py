"""Streaming operator-graph executor for Dataset map stages.

Reference: python/ray/data/_internal/execution/streaming_executor.py:100
(operator graph with concurrent stages), backpressure_policy/ (resource
backpressure), execution/operators/map_operator.py:196 (task- and
actor-pool map operators).

Shape: a chain of map operators connected by in-memory ref queues. The
driver's scheduling loop submits block tasks for EVERY operator each
tick, so stage N+1 processes block i while stage N processes block i+1
— no barrier between stages. Three forms of backpressure bound memory:

- per-operator in-flight task budgets (concurrency caps),
- object-store pressure: while the local store is above the high
  watermark, no new tasks are submitted (completions drain it) — with a
  one-task escape hatch so an over-full store cannot deadlock progress,
- consumer pull: outputs sit in the final queue until the caller's
  iterator takes them, and the bounded queues upstream fill up behind
  it.

Tasks complete out of order; each operator tracks completions as they
land and (by default) releases them downstream in submission order, so
a straggler delays only the ordering boundary, not execution.
"""

from __future__ import annotations

import collections
import logging
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data._internal.executor import _apply_block_fn, _pack

logger = logging.getLogger(__name__)

# Backpressure knobs (reference: concurrency_cap_backpressure_policy.py
# and the resource-manager object-store budget).
DEFAULT_OP_CONCURRENCY = 8
STORE_HIGH_WATERMARK = 0.8
MAX_QUEUED_PER_OP = 32


@ray_tpu.remote
class _MapActor:
    """Actor-pool worker for stateful transforms (reference:
    map_operator.py actor pool): the callable class is constructed ONCE
    and reused across blocks; ``wrapper(instance, block)`` carries the
    stage's batch-format/slicing logic."""

    def __init__(self, ctor_bytes: bytes, wrapper_bytes: bytes):
        from ray_tpu._private.serialization import loads_function

        self._instance = loads_function(ctor_bytes)()
        self._wrapper = loads_function(wrapper_bytes)

    def apply(self, block):
        return self._wrapper(self._instance, block)


class MapOp:
    """One physical map stage: bounded in-flight tasks over blocks."""

    def __init__(self, name: str, fn: Optional[Callable] = None,
                 actor_cls: Optional[type] = None,
                 actor_wrapper: Optional[Callable] = None,
                 concurrency: int = DEFAULT_OP_CONCURRENCY,
                 preserve_order: bool = True):
        self.name = name
        self.concurrency = max(1, concurrency)
        self.preserve_order = preserve_order
        self._fn_bytes = _pack(fn) if fn is not None else None
        self._actor_cls = actor_cls
        self._actor_wrapper = actor_wrapper
        self._actors: List[Any] = []
        self._actor_load: Dict[int, int] = {}
        self.pending_in: collections.deque = collections.deque()
        self.inflight: Dict[Any, int] = {}  # ref -> submit seq
        self._inflight_actor: Dict[Any, int] = {}  # ref -> actor index
        self._ready: Dict[int, Any] = {}  # seq -> ref (completed)
        self._unordered_ready: collections.deque = collections.deque()
        self._next_seq = 0
        self._next_emit = 0
        self.input_done = False

    # -- feeding -------------------------------------------------------
    def wants_input(self) -> bool:
        return (not self.input_done
                and len(self.pending_in) < MAX_QUEUED_PER_OP)

    def add_input(self, ref: Any) -> None:
        self.pending_in.append(ref)

    def close_input(self) -> None:
        self.input_done = True

    # -- scheduling ----------------------------------------------------
    def _ensure_actors(self) -> None:
        if self._actors or self._actor_cls is None:
            return
        ctor = _pack(self._actor_cls)
        wrapper = _pack(self._actor_wrapper)
        self._actors = [_MapActor.remote(ctor, wrapper)
                        for _ in range(self.concurrency)]
        self._actor_load = {i: 0 for i in range(len(self._actors))}

    def schedule(self, under_pressure: bool, force_one: bool,
                 downstream_free: int) -> bool:
        """Submit tasks within budget; returns True if any submitted.
        ``downstream_free``: remaining queue slots in the next operator —
        the inter-stage backpressure bound (an upstream op must not
        produce blocks its consumer has no room to queue)."""
        submitted = False
        while self.pending_in and \
                len(self.inflight) + self._backlog() < self.concurrency:
            # _backlog counts completed-but-unemitted outputs: under
            # preserve_order a head-of-line straggler must throttle new
            # submissions, not let the ready set grow without bound
            if len(self.inflight) >= max(0, downstream_free):
                break
            if under_pressure and not (force_one and not submitted):
                break
            ref = self.pending_in.popleft()
            if self._actor_cls is not None:
                self._ensure_actors()
                idx = min(self._actor_load,
                          key=lambda i: self._actor_load[i])
                out = self._actors[idx].apply.remote(ref)
                self._actor_load[idx] += 1
                self._inflight_actor[out] = idx
            else:
                out = _apply_block_fn.remote(self._fn_bytes, ref)
            self.inflight[out] = self._next_seq
            self._next_seq += 1
            submitted = True
        return submitted

    def absorb_completions(self) -> bool:
        """Collect finished tasks (out-of-order) into the ready set."""
        if not self.inflight:
            return False
        refs = list(self.inflight)
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0,
                               fetch_local=False)
        for r in done:
            seq = self.inflight.pop(r)
            idx = self._inflight_actor.pop(r, None)
            if idx is not None:
                self._actor_load[idx] -= 1
            if self.preserve_order:
                self._ready[seq] = r
            else:
                self._unordered_ready.append(r)
        return bool(done)

    def take_outputs(self) -> List[Any]:
        out: List[Any] = []
        if self.preserve_order:
            while self._next_emit in self._ready:
                out.append(self._ready.pop(self._next_emit))
                self._next_emit += 1
        else:
            while self._unordered_ready:
                out.append(self._unordered_ready.popleft())
        return out

    def _backlog(self) -> int:
        return len(self._ready) + len(self._unordered_ready)

    def exhausted(self) -> bool:
        return (self.input_done and not self.pending_in
                and not self.inflight and not self._ready
                and not self._unordered_ready)

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        self._actors = []


def _store_pressure() -> bool:
    """True while the local object store is above the high watermark
    (reference: backpressure on object_store_memory usage)."""
    from ray_tpu._private import worker as wm

    w = wm.global_worker
    if w is None or not getattr(w, "connected", False):
        return False
    plasma = getattr(getattr(w, "core", None), "plasma", None)
    if plasma is None:
        return False
    try:
        m = plasma.metrics()
    except Exception:  # noqa: BLE001
        return False
    cap = m.get("capacity") or 0
    return cap > 0 and m.get("allocated", 0) / cap > STORE_HIGH_WATERMARK


class StreamingExecutor:
    """Drives a chain of MapOps over a source-ref iterator, yielding
    final output refs as they become available."""

    def __init__(self, ops: List[MapOp]):
        self.ops = ops

    def execute(self, source: Iterator[Any]) -> Iterator[Any]:
        ops = self.ops
        src = iter(source)
        src_done = False
        for op in ops:
            # actor pools spin up eagerly so their (seconds-long) start
            # overlaps with upstream compute instead of serializing it
            op._ensure_actors()
        try:
            while True:
                progress = False
                pressure = _store_pressure()  # once per tick
                # feed the head operator from the source
                while not src_done and ops[0].wants_input() \
                        and not pressure:
                    try:
                        ops[0].add_input(next(src))
                        progress = True
                    except StopIteration:
                        src_done = True
                        ops[0].close_input()
                if not src_done and ops[0].wants_input() \
                        and not any(op.inflight for op in ops):
                    # escape hatch: a full store with nothing in flight
                    # must still admit one block or nothing ever drains
                    try:
                        ops[0].add_input(next(src))
                        progress = True
                    except StopIteration:
                        src_done = True
                        ops[0].close_input()
                total_inflight = sum(len(op.inflight) for op in ops)
                allow_force = total_inflight == 0  # ONE task total under
                # pressure, across all ops — not one per op
                for k, op in enumerate(ops):
                    free = (MAX_QUEUED_PER_OP - len(ops[k + 1].pending_in)
                            if k + 1 < len(ops) else MAX_QUEUED_PER_OP)
                    if op.schedule(pressure, force_one=allow_force,
                                   downstream_free=free):
                        progress = True
                        allow_force = False
                    if op.absorb_completions():
                        progress = True
                    outs = op.take_outputs()
                    if outs:
                        progress = True
                    if k + 1 < len(ops):
                        for r in outs:
                            ops[k + 1].add_input(r)
                        if op.exhausted() and not ops[k + 1].input_done:
                            ops[k + 1].close_input()
                    else:
                        yield from outs
                if all(op.exhausted() for op in ops) and src_done:
                    return
                if not progress:
                    # block until SOME inflight task finishes
                    inflight = [r for op in ops for r in op.inflight]
                    if inflight:
                        ray_tpu.wait(inflight, num_returns=1, timeout=0.5,
                                     fetch_local=False)
                    # else: only queued work gated by pressure — loop
                    # re-enters schedule(force_one=...) to make progress
        finally:
            for op in ops:
                op.shutdown()
