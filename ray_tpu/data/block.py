"""Blocks — the unit of data in ray_tpu.data.

Reference: python/ray/data/block.py + _internal/arrow_block.py. A block
is a batch of rows stored columnar; here the canonical in-memory format
is a dict of numpy arrays (TPU-first: numpy feeds jax.device_put
directly, zero-copy through the shared-memory object store thanks to
pickle-5 buffers). Pyarrow tables / pandas frames convert on the edges.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

Block = Dict[str, np.ndarray]


def block_from_rows(rows: List[Any]) -> Block:
    """List of rows (dicts or scalars) → columnar block."""
    if not rows:
        return {}
    first = rows[0]
    if isinstance(first, dict):
        keys = list(first.keys())
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"item": np.asarray(rows)}


def block_to_rows(block: Block) -> List[Any]:
    if not block:
        return []
    keys = list(block.keys())
    n = block_num_rows(block)
    if keys == ["item"]:
        return [block["item"][i] for i in range(n)]
    return [{k: block[k][i] for k in keys} for i in range(n)]


def block_num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_size_bytes(block: Block) -> int:
    return sum(v.nbytes if hasattr(v, "nbytes") else 0 for v in block.values())


def block_slice(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return {}
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def block_select(block: Block, cols: List[str]) -> Block:
    return {k: block[k] for k in cols}


def normalize_batch(batch: Any) -> Block:
    """User map_batches output → block (accept dict / numpy / pandas / arrow)."""
    if batch is None:
        return {}
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, np.ndarray):
        return {"item": batch}
    # pandas
    if hasattr(batch, "to_dict") and hasattr(batch, "columns"):
        return {c: np.asarray(batch[c]) for c in batch.columns}
    # pyarrow table
    if hasattr(batch, "column_names") and hasattr(batch, "to_pydict"):
        return {c: np.asarray(v) for c, v in batch.to_pydict().items()}
    if isinstance(batch, (list, tuple)):
        return block_from_rows(list(batch))
    raise TypeError(f"Unsupported batch type: {type(batch)}")


def to_batch_format(block: Block, batch_format: Optional[str]):
    """Block → user-facing batch ("numpy" dict, "pandas", "pyarrow")."""
    if batch_format in (None, "numpy", "default"):
        return dict(block)
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in block.items()})
    if batch_format == "pyarrow":
        import pyarrow as pa

        return pa.table({k: list(v) for k, v in block.items()})
    raise ValueError(f"Unknown batch_format: {batch_format}")
