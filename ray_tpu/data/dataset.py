"""Dataset — lazy, distributed, blocks-over-object-store data plane.

Reference: python/ray/data/dataset.py:202 (`Dataset`), lazy logical plan
(_internal/logical/), streaming execution (streaming_executor.py:100).

Design here: a Dataset is (source block refs, chain of logical ops).
Consecutive per-block ops FUSE into one remote task per block (the
reference planner's map-fusion); all-to-all ops (repartition, shuffle,
sort, groupby) are barriers. Blocks are dicts of numpy arrays riding the
shared-memory object store; `iter_batches` feeds jax/TPU input pipelines
without copies.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_concat,
    block_from_rows,
    block_num_rows,
    block_size_bytes,
    block_slice,
    block_take,
    block_to_rows,
    normalize_batch,
    to_batch_format,
)
from ray_tpu.data._internal.executor import Executor


# ---------------------------------------------------------------------------
# Logical ops
# ---------------------------------------------------------------------------
class _Op:
    pass


class _MapBlocks(_Op):
    """Per-block transform (map/map_batches/filter/flat_map fuse here)."""

    def __init__(self, fn: Callable[[Block], Block], name: str):
        self.fn = fn
        self.name = name


class _AllToAll(_Op):
    """Barrier op: takes ALL input blocks, returns new blocks."""

    def __init__(self, fn: Callable[[List[Block]], List[Block]], name: str):
        self.fn = fn
        self.name = name


class _Limit(_Op):
    def __init__(self, n: int):
        self.n = n


class Dataset:
    """Lazy distributed dataset (reference: data/dataset.py:202)."""

    def __init__(self, block_refs: List[Any], ops: Optional[List[_Op]] = None):
        self._source_refs = list(block_refs)
        self._ops: List[_Op] = list(ops or [])
        self._executor = Executor()

    # -- plan building ------------------------------------------------
    def _with(self, op: _Op) -> "Dataset":
        return Dataset(self._source_refs, self._ops + [op])

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_format: Optional[str] = None,
        batch_size: Optional[int] = None,
        fn_kwargs: Optional[Dict] = None,
        **_ignored,
    ) -> "Dataset":
        """Apply fn to batches (reference: dataset.py:531). With
        batch_size=None the whole block is one batch (fastest on TPU —
        blocks are already sized for the store)."""
        kw = fn_kwargs or {}

        def _apply(block: Block) -> Block:
            if not block_num_rows(block):
                return block
            if batch_size is None:
                return normalize_batch(fn(to_batch_format(block, batch_format), **kw))
            outs = []
            n = block_num_rows(block)
            for s in range(0, n, batch_size):
                piece = block_slice(block, s, min(s + batch_size, n))
                outs.append(normalize_batch(fn(to_batch_format(piece, batch_format), **kw)))
            return block_concat(outs)

        return self._with(_MapBlocks(_apply, f"MapBatches({getattr(fn, '__name__', 'fn')})"))

    def map(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            return block_from_rows([fn(r) for r in block_to_rows(block)])

        return self._with(_MapBlocks(_apply, "Map"))

    def flat_map(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            out = []
            for r in block_to_rows(block):
                out.extend(fn(r))
            return block_from_rows(out)

        return self._with(_MapBlocks(_apply, "FlatMap"))

    def filter(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            return block_from_rows([r for r in block_to_rows(block) if fn(r)])

        return self._with(_MapBlocks(_apply, "Filter"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with(_MapBlocks(lambda b: {k: b[k] for k in cols}, "Select"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._with(
            _MapBlocks(lambda b: {k: v for k, v in b.items() if k not in cols}, "Drop")
        )

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]) -> "Dataset":
        def _apply(block: Block) -> Block:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self._with(_MapBlocks(_apply, f"AddColumn({name})"))

    def limit(self, n: int) -> "Dataset":
        return self._with(_Limit(n))

    # -- all-to-all ----------------------------------------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        def _repart(blocks: List[Block]) -> List[Block]:
            whole = block_concat(blocks)
            n = block_num_rows(whole)
            if n == 0:
                return []
            splits = np.array_split(np.arange(n), num_blocks)
            return [block_take(whole, idx) for idx in splits if len(idx)]

        return self._with(_AllToAll(_repart, f"Repartition({num_blocks})"))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        def _shuf(blocks: List[Block]) -> List[Block]:
            whole = block_concat(blocks)
            n = block_num_rows(whole)
            if n == 0:
                return []
            rng = np.random.RandomState(seed)
            perm = rng.permutation(n)
            k = max(1, len(blocks))
            return [block_take(whole, idx) for idx in np.array_split(perm, k)]

        return self._with(_AllToAll(_shuf, "RandomShuffle"))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        def _sort(blocks: List[Block]) -> List[Block]:
            whole = block_concat(blocks)
            if not block_num_rows(whole):
                return []
            order = np.argsort(whole[key], kind="stable")
            if descending:
                order = order[::-1]
            k = max(1, len(blocks))
            return [block_take(whole, idx) for idx in np.array_split(order, k)]

        return self._with(_AllToAll(_sort, f"Sort({key})"))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._iter_output_refs())
        for o in others:
            refs.extend(o._iter_output_refs())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        a = self.materialize_block()
        b = other.materialize_block()
        merged = dict(a)
        for k, v in b.items():
            merged[k if k not in merged else f"{k}_1"] = v
        return Dataset([ray_tpu.put(merged)])

    # -- execution -----------------------------------------------------
    def _iter_output_refs(self) -> Iterator[Any]:
        """Execute the plan, yielding output block refs streamingly.
        Consecutive _MapBlocks fuse into one task per block."""
        refs: Iterator[Any] = iter(self._source_refs)
        i = 0
        ops = self._ops
        local = _use_local_exec()
        while i < len(ops):
            op = ops[i]
            if isinstance(op, _MapBlocks):
                fused = [op.fn]
                j = i + 1
                while j < len(ops) and isinstance(ops[j], _MapBlocks):
                    fused.append(ops[j].fn)
                    j += 1

                def chain(block, fns=tuple(fused)):
                    for f in fns:
                        block = f(block)
                    return block

                refs = self._executor.map_refs(chain, refs, local=local)
                i = j
            elif isinstance(op, _AllToAll):
                blocks = [ray_tpu.get(r) for r in refs]
                out_blocks = op.fn(blocks)
                refs = iter([ray_tpu.put(b) for b in out_blocks])
                i += 1
            elif isinstance(op, _Limit):
                refs = _limit_refs(refs, op.n)
                i += 1
            else:
                raise TypeError(op)
        return refs

    def iter_blocks(self) -> Iterator[Block]:
        for r in self._iter_output_refs():
            yield ray_tpu.get(r)

    def iter_rows(self) -> Iterator[Any]:
        for b in self.iter_blocks():
            yield from block_to_rows(b)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: Optional[str] = None,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        """Re-batch the block stream to batch_size (reference:
        dataset.py:5981). The carry-over path avoids concatenating more
        than one pending block at a time."""
        rng = np.random.RandomState(local_shuffle_seed)
        carry: Block = {}
        for block in self.iter_blocks():
            if local_shuffle_buffer_size:
                n = block_num_rows(block)
                if n:
                    block = block_take(block, rng.permutation(n))
            carry = block_concat([carry, block]) if carry else block
            if batch_size is None:
                if block_num_rows(carry):
                    yield to_batch_format(carry, batch_format)
                carry = {}
                continue
            while block_num_rows(carry) >= batch_size:
                yield to_batch_format(block_slice(carry, 0, batch_size), batch_format)
                carry = block_slice(carry, batch_size, block_num_rows(carry))
        if block_num_rows(carry) and not drop_last and batch_size is not None:
            yield to_batch_format(carry, batch_format)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         drop_last: bool = True) -> Iterator[Any]:
        """TPU ingest: yields dicts of jax arrays, device_put with the
        given sharding (the Train ingest path — no reference equivalent;
        torch iterators are replaced by this)."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}

    # -- consumption ---------------------------------------------------
    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def sum(self, col: str) -> float:
        return float(np.sum([b[col].sum() for b in self.iter_blocks() if block_num_rows(b)]))

    def min(self, col: str) -> float:
        return float(np.min([b[col].min() for b in self.iter_blocks() if block_num_rows(b)]))

    def max(self, col: str) -> float:
        return float(np.max([b[col].max() for b in self.iter_blocks() if block_num_rows(b)]))

    def mean(self, col: str) -> float:
        tot, cnt = 0.0, 0
        for b in self.iter_blocks():
            n = block_num_rows(b)
            if n:
                tot += float(b[col].sum())
                cnt += n
        return tot / max(cnt, 1)

    def schema(self) -> Dict[str, Any]:
        for b in self.iter_blocks():
            if block_num_rows(b):
                return {k: (v.dtype, v.shape[1:]) for k, v in b.items()}
        return {}

    def num_blocks(self) -> int:
        return sum(1 for _ in self._iter_output_refs())

    def size_bytes(self) -> int:
        return sum(block_size_bytes(b) for b in self.iter_blocks())

    def materialize(self) -> "Dataset":
        """Execute the plan; result holds concrete block refs."""
        return Dataset(list(self._iter_output_refs()))

    def materialize_block(self) -> Block:
        return block_concat(list(self.iter_blocks()))

    def split(self, n: int, *, locality_hints=None) -> List["Dataset"]:
        """Split into n datasets (reference: dataset.py split for per-worker
        ingest shards)."""
        refs = list(self._iter_output_refs())
        if len(refs) < n:
            whole = block_concat([ray_tpu.get(r) for r in refs])
            rows = block_num_rows(whole)
            idx = np.array_split(np.arange(rows), n)
            return [Dataset([ray_tpu.put(block_take(whole, i))]) for i in idx]
        parts = np.array_split(np.arange(len(refs)), n)
        return [Dataset([refs[i] for i in p]) for p in parts]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None) -> Tuple["Dataset", "Dataset"]:
        whole = self.materialize_block()
        n = block_num_rows(whole)
        idx = np.arange(n)
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        k = int(n * (1 - test_size))
        return (
            Dataset([ray_tpu.put(block_take(whole, idx[:k]))]),
            Dataset([ray_tpu.put(block_take(whole, idx[k:]))]),
        )

    def __repr__(self) -> str:
        names = [getattr(op, "name", type(op).__name__) for op in self._ops]
        return f"Dataset(blocks={len(self._source_refs)}, plan={' -> '.join(names) or 'source'})"

    stats = __repr__


class GroupedData:
    """Sort-based groupby (reference: data grouped_data.py)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, agg_fn: Callable[[Block], Dict[str, Any]], suffix: str) -> Dataset:
        whole = self._ds.materialize_block()
        if not block_num_rows(whole):
            return Dataset([])
        keys = whole[self._key]
        uniq, inverse = np.unique(keys, return_inverse=True)
        rows = []
        for gi, kv in enumerate(uniq):
            grp = block_take(whole, np.where(inverse == gi)[0])
            row = {self._key: kv}
            row.update(agg_fn(grp))
            rows.append(row)
        return Dataset([ray_tpu.put(block_from_rows(rows))])

    def count(self) -> Dataset:
        return self._agg(lambda g: {"count()": block_num_rows(g)}, "count")

    def sum(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"sum({col})": g[col].sum()}, "sum")

    def mean(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"mean({col})": g[col].mean()}, "mean")

    def max(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"max({col})": g[col].max()}, "max")

    def min(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"min({col})": g[col].min()}, "min")


def _limit_refs(refs: Iterator[Any], n: int) -> Iterator[Any]:
    remaining = n
    for r in refs:
        if remaining <= 0:
            return
        block = ray_tpu.get(r)
        rows = block_num_rows(block)
        if rows <= remaining:
            remaining -= rows
            yield r
        else:
            yield ray_tpu.put(block_slice(block, 0, remaining))
            remaining = 0


def _use_local_exec() -> bool:
    """Local mode (or no cluster) executes the plan in-process."""
    from ray_tpu._private import worker as wm

    w = wm.global_worker
    if w is None or not w.connected:
        return True
    return getattr(w, "mode", None) == wm.LOCAL_MODE
