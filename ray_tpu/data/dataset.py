"""Dataset — lazy, distributed, blocks-over-object-store data plane.

Reference: python/ray/data/dataset.py:202 (`Dataset`), lazy logical plan
(_internal/logical/), streaming execution (streaming_executor.py:100).

Design here: a Dataset is (source block refs, chain of logical ops).
Consecutive per-block ops FUSE into one remote task per block (the
reference planner's map-fusion); all-to-all ops (repartition, shuffle,
sort, groupby) are barriers. Blocks are dicts of numpy arrays riding the
shared-memory object store; `iter_batches` feeds jax/TPU input pipelines
without copies.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    block_concat,
    block_from_rows,
    block_num_rows,
    block_size_bytes,
    block_slice,
    block_take,
    block_to_rows,
    normalize_batch,
    to_batch_format,
)
from ray_tpu.data._internal.executor import Executor


# ---------------------------------------------------------------------------
# Logical ops
# ---------------------------------------------------------------------------
class _Op:
    pass


class _MapBlocks(_Op):
    """Per-block transform (map/map_batches/filter/flat_map fuse here).
    ``concurrency`` (optional) caps the stage's in-flight task budget;
    a fused chain runs at the smallest cap any member requested."""

    def __init__(self, fn: Callable[[Block], Block], name: str,
                 concurrency: Optional[int] = None):
        self.fn = fn
        self.name = name
        self.concurrency = concurrency


class _ActorMapBlocks(_Op):
    """Stateful per-block transform on an actor pool (reference:
    map_operator.py:196 actor pool — ``compute`` with a callable class):
    ``cls()`` is constructed once per pool actor, ``wrapper(instance,
    block)`` applies it to each block. Never fuses with neighbors."""

    def __init__(self, cls: type, wrapper: Callable, name: str,
                 concurrency: int):
        self.cls = cls
        self.wrapper = wrapper
        self.name = name
        self.concurrency = concurrency


class _Shuffle(_Op):
    """All-to-all op as a distributed two-stage shuffle: ``partition_fn``
    splits each block into k parts (map tasks), ``reduce_fn`` merges part
    j of every block (reduce tasks). ``prepare`` may inspect the input
    refs first (e.g. sort boundary sampling) and returns the actual
    partition fn. Blocks never materialize on the driver (reference:
    _internal/planner/{sort,random_shuffle}.py)."""

    def __init__(self, partition_fn, reduce_fn, name: str,
                 num_outputs: Optional[int] = None, prepare=None):
        self.partition_fn = partition_fn
        self.reduce_fn = reduce_fn
        self.num_outputs = num_outputs
        self.prepare = prepare
        self.name = name


class _Limit(_Op):
    def __init__(self, n: int):
        self.n = n


class Dataset:
    """Lazy distributed dataset (reference: data/dataset.py:202)."""

    def __init__(self, block_refs: List[Any], ops: Optional[List[_Op]] = None):
        self._source_refs = list(block_refs)
        self._ops: List[_Op] = list(ops or [])
        self._executor = Executor()

    # -- plan building ------------------------------------------------
    def _with(self, op: _Op) -> "Dataset":
        return Dataset(self._source_refs, self._ops + [op])

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_format: Optional[str] = None,
        batch_size: Optional[int] = None,
        fn_kwargs: Optional[Dict] = None,
        concurrency: Optional[Union[int, Tuple[int, int]]] = None,
        **_ignored,
    ) -> "Dataset":
        """Apply fn to batches (reference: dataset.py:531). With
        batch_size=None the whole block is one batch (fastest on TPU —
        blocks are already sized for the store).

        ``fn`` may be a callable CLASS (reference: actor compute
        strategy): it is constructed once per pool actor and reused
        across blocks — ``concurrency`` (int, or the reference's
        (min, max) form; we size at the max) sets the pool size, or the
        in-flight task budget for plain functions."""
        kw = fn_kwargs or {}

        def _call_batches(call, block: Block) -> Block:
            if not block_num_rows(block):
                return block
            if batch_size is None:
                return normalize_batch(call(to_batch_format(block, batch_format), **kw))
            outs = []
            n = block_num_rows(block)
            for s in range(0, n, batch_size):
                piece = block_slice(block, s, min(s + batch_size, n))
                outs.append(normalize_batch(call(to_batch_format(piece, batch_format), **kw)))
            return block_concat(outs)

        name = f"MapBatches({getattr(fn, '__name__', 'fn')})"
        concurrency = _normalize_concurrency(concurrency)
        if isinstance(fn, type):
            return self._with(_ActorMapBlocks(
                fn, _call_batches, name, concurrency or 2))

        def _apply(block: Block) -> Block:
            return _call_batches(fn, block)

        return self._with(_MapBlocks(_apply, name, concurrency=concurrency))

    def map(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            return block_from_rows([fn(r) for r in block_to_rows(block)])

        return self._with(_MapBlocks(_apply, "Map"))

    def flat_map(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            out = []
            for r in block_to_rows(block):
                out.extend(fn(r))
            return block_from_rows(out)

        return self._with(_MapBlocks(_apply, "FlatMap"))

    def filter(self, fn: Callable) -> "Dataset":
        def _apply(block: Block) -> Block:
            return block_from_rows([r for r in block_to_rows(block) if fn(r)])

        return self._with(_MapBlocks(_apply, "Filter"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with(_MapBlocks(lambda b: {k: b[k] for k in cols}, "Select"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._with(
            _MapBlocks(lambda b: {k: v for k, v in b.items() if k not in cols}, "Drop")
        )

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]) -> "Dataset":
        def _apply(block: Block) -> Block:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out

        return self._with(_MapBlocks(_apply, f"AddColumn({name})"))

    def limit(self, n: int) -> "Dataset":
        return self._with(_Limit(n))

    # -- all-to-all (distributed two-stage shuffles) -------------------
    def repartition(self, num_blocks: int) -> "Dataset":
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")

        def _part(block: Block, k: int, idx: int) -> List[Block]:
            n = block_num_rows(block)
            return [block_take(block, i) for i in np.array_split(np.arange(n), k)]

        return self._with(_Shuffle(
            _part, block_concat, f"Repartition({num_blocks})",
            num_outputs=num_blocks,
        ))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        def _part(block: Block, k: int, idx: int) -> List[Block]:
            n = block_num_rows(block)
            # per-BLOCK-INDEX rng: every block must draw a different
            # assignment stream or same-offset rows stay co-located
            rng = np.random.RandomState(
                None if seed is None else (seed * 1_000_003 + idx) % (2**31)
            )
            assign = rng.randint(0, k, size=n)
            return [block_take(block, np.where(assign == j)[0]) for j in range(k)]

        def _reduce(parts: List[Block]) -> Block:
            merged = block_concat(parts)
            n = block_num_rows(merged)
            if not n:
                return merged
            rng = np.random.RandomState(seed)
            return block_take(merged, rng.permutation(n))

        return self._with(_Shuffle(_part, _reduce, "RandomShuffle"))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        def _prepare(refs: List[Any]) -> Callable:
            # sample keys from each block to pick range boundaries
            # (reference: sample-based sort partitioning, planner/sort.py)
            def _sample(block: Block) -> Block:
                vals = block.get(key)
                if vals is None or not len(vals):
                    return {}
                idx = np.linspace(0, len(vals) - 1, min(64, len(vals))).astype(int)
                return {"s": np.asarray(vals)[idx]}

            samp_refs = list(self._executor.map_refs(_sample, iter(refs),
                                                     local=_use_local_exec()))
            sample_arrays = [
                s["s"] for s in (ray_tpu.get(r) for r in samp_refs) if s
            ]
            samples = np.concatenate(sample_arrays) if sample_arrays else np.array([])
            # boundaries once here, not per map task; evenly-spaced order
            # statistics (not np.quantile) so string keys sort too
            k_out = max(1, len(refs))
            if len(samples):
                ss = np.sort(samples)
                cut = np.linspace(0, len(ss) - 1, k_out + 1).astype(int)[1:-1]
                bounds = ss[cut]
            else:
                bounds = samples

            def _part(block: Block, k: int, idx: int) -> List[Block]:
                if not block_num_rows(block):
                    return [block] * k
                assign = np.searchsorted(bounds, block[key], side="right")
                if descending:
                    assign = (k - 1) - assign  # reversed range order
                return [block_take(block, np.where(assign == j)[0]) for j in range(k)]

            return _part

        def _reduce(parts: List[Block]) -> Block:
            merged = block_concat(parts)
            if not block_num_rows(merged):
                return merged
            order = np.argsort(merged[key], kind="stable")
            if descending:
                order = order[::-1]
            return block_take(merged, order)

        return self._with(_Shuffle(None, _reduce, f"Sort({key})", prepare=_prepare))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join (reference: data/_internal joins via
        hash shuffle; data/dataset.py Dataset.join). Both sides
        hash-partition on the key (map tasks), matching partitions join
        pairwise (one task per bucket) — no driver materialization of
        either table."""
        if how not in ("inner", "left", "outer"):
            raise ValueError(f"unsupported join how={how!r}")

        left_refs = list(self._iter_output_refs())
        right_refs = list(other._iter_output_refs())
        k = num_partitions or max(len(left_refs), len(right_refs), 1)

        @ray_tpu.remote(num_returns=k)
        def _part(block: Block, key: str, k: int):
            n = block_num_rows(block)
            if not n:
                # keep the SCHEMA even with zero rows: a bucket whose
                # side is empty must still know that side's columns, or
                # a left/outer join there drops them instead of NaN-ing
                parts = [{c: v[:0] for c, v in block.items()}
                         for _ in range(k)]
            else:
                from pandas.util import hash_array

                vals = np.asarray(block[key])
                # canonicalize BEFORE hashing: both sides of the join
                # must bucket equal keys identically even when their
                # dtypes differ (int64 5 joining float64 5.0 — common
                # after parquet/CSV ingestion)
                if vals.dtype.kind in "iufb":
                    vals = vals.astype(np.float64)
                assign = (hash_array(vals) % k).astype(np.int64)
                parts = [block_take(block, np.where(assign == j)[0])
                         for j in range(k)]
            return parts if k > 1 else parts[0]

        @ray_tpu.remote
        def _join_bucket(key: str, how: str, n_left: int, *parts):
            import pandas as pd

            def side_df(side):
                data = block_concat(
                    [p for p in side if block_num_rows(p)])
                return pd.DataFrame(data) if data \
                    else pd.DataFrame({key: []})

            lefts, rights = parts[:n_left], parts[n_left:]
            if not any(block_num_rows(p) for p in parts):
                return {}
            merged = side_df(lefts).merge(side_df(rights), on=key,
                                          how=how, suffixes=("", "_right"))
            # a bucket whose side had ZERO rows lost that side's columns
            # in the merge — every part still carries its schema (see
            # _part's zero-row slices), so restore them as NaN to keep
            # bucket schemas consistent
            for p in parts:
                for c in p:
                    if c not in merged.columns:
                        merged[c] = np.nan
            return {c: merged[c].to_numpy() for c in merged.columns}

        left_parts = [_part.remote(r, on, k) for r in left_refs]
        right_parts = [_part.remote(r, on, k) for r in right_refs]
        if k == 1:
            left_parts = [[p] for p in left_parts]
            right_parts = [[p] for p in right_parts]
        out_refs = []
        for j in np.arange(k):
            bucket_left = [ps[j] for ps in left_parts]
            bucket_right = [ps[j] for ps in right_parts]
            out_refs.append(_join_bucket.remote(
                on, how, len(bucket_left), *bucket_left, *bucket_right))
        return Dataset(out_refs)

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._iter_output_refs())
        for o in others:
            refs.extend(o._iter_output_refs())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        a = self.materialize_block()
        b = other.materialize_block()
        merged = dict(a)
        for k, v in b.items():
            merged[k if k not in merged else f"{k}_1"] = v
        return Dataset([ray_tpu.put(merged)])

    # -- execution -----------------------------------------------------
    def _iter_output_refs(self) -> Iterator[Any]:
        """Execute the plan, yielding output block refs streamingly.

        Consecutive _MapBlocks fuse into one task per block; runs of
        map stages (fused chains + actor-pool stages) execute on the
        STREAMING executor — an operator graph whose stages run
        concurrently with per-op in-flight budgets and object-store
        backpressure (reference: streaming_executor.py:100). Shuffles
        are barriers between streaming segments."""
        refs: Iterator[Any] = iter(self._source_refs)
        i = 0
        ops = self._ops
        local = _use_local_exec()
        while i < len(ops):
            op = ops[i]
            if isinstance(op, (_MapBlocks, _ActorMapBlocks)):
                # collect the maximal run of map-like stages into one
                # streaming segment
                phys: List[Any] = []
                j = i
                while j < len(ops):
                    if isinstance(ops[j], _MapBlocks):
                        fused = [ops[j].fn]
                        caps = [ops[j].concurrency]
                        j += 1
                        while j < len(ops) and isinstance(ops[j], _MapBlocks):
                            fused.append(ops[j].fn)
                            caps.append(ops[j].concurrency)
                            j += 1

                        def chain(block, fns=tuple(fused)):
                            for f in fns:
                                block = f(block)
                            return block

                        caps = [c for c in caps if c]
                        phys.append(("fn", chain, min(caps) if caps else None))
                    elif isinstance(ops[j], _ActorMapBlocks):
                        phys.append(("actor", ops[j], None))
                        j += 1
                    else:
                        break
                refs = self._run_map_segment(phys, refs, local)
                i = j
            elif isinstance(op, _Shuffle):
                in_refs = list(refs)
                part_fn = op.partition_fn
                if op.prepare is not None:
                    part_fn = op.prepare(in_refs)
                refs = self._executor.shuffle_refs(
                    in_refs, part_fn, op.reduce_fn,
                    num_outputs=op.num_outputs, local=local,
                )
                i += 1
            elif isinstance(op, _Limit):
                refs = _limit_refs(refs, op.n)
                i += 1
            else:
                raise TypeError(op)
        return refs

    def _run_map_segment(self, phys: List[Any], refs: Iterator[Any],
                         local: bool) -> Iterator[Any]:
        if local:
            # in-process short circuit: construct actor classes once,
            # map serially
            out = refs
            for kind, payload, _ in phys:
                if kind == "fn":
                    out = self._executor.map_refs(payload, out, local=True)
                else:
                    inst = payload.cls()
                    wrapper = payload.wrapper
                    out = self._executor.map_refs(
                        functools.partial(wrapper, inst), out, local=True)
            return out
        from ray_tpu.data._internal.streaming_executor import (
            MapOp,
            StreamingExecutor,
        )

        map_ops: List[MapOp] = []
        for kind, payload, cap in phys:
            if kind == "fn":
                from ray_tpu.data._internal.streaming_executor import (
                    DEFAULT_OP_CONCURRENCY,
                )

                map_ops.append(MapOp(
                    "map", fn=payload,
                    concurrency=cap or DEFAULT_OP_CONCURRENCY))
            else:
                map_ops.append(MapOp(
                    payload.name, actor_cls=payload.cls,
                    actor_wrapper=payload.wrapper,
                    concurrency=payload.concurrency))
        return StreamingExecutor(map_ops).execute(refs)

    def iter_blocks(self) -> Iterator[Block]:
        for r in self._iter_output_refs():
            yield ray_tpu.get(r)

    def iter_rows(self) -> Iterator[Any]:
        for b in self.iter_blocks():
            yield from block_to_rows(b)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: Optional[str] = None,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        """Re-batch the block stream to batch_size (reference:
        dataset.py:5981). The carry-over path avoids concatenating more
        than one pending block at a time."""
        rng = np.random.RandomState(local_shuffle_seed)
        carry: Block = {}
        for block in self.iter_blocks():
            if local_shuffle_buffer_size:
                n = block_num_rows(block)
                if n:
                    block = block_take(block, rng.permutation(n))
            carry = block_concat([carry, block]) if carry else block
            if batch_size is None:
                if block_num_rows(carry):
                    yield to_batch_format(carry, batch_format)
                carry = {}
                continue
            while block_num_rows(carry) >= batch_size:
                yield to_batch_format(block_slice(carry, 0, batch_size), batch_format)
                carry = block_slice(carry, batch_size, block_num_rows(carry))
        if block_num_rows(carry) and not drop_last and batch_size is not None:
            yield to_batch_format(carry, batch_format)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         drop_last: bool = True) -> Iterator[Any]:
        """TPU ingest: yields dicts of jax arrays, device_put with the
        given sharding (the Train ingest path — no reference equivalent;
        torch iterators are replaced by this)."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}

    # -- consumption ---------------------------------------------------
    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def sum(self, col: str) -> float:
        return float(np.sum([b[col].sum() for b in self.iter_blocks() if block_num_rows(b)]))

    def min(self, col: str) -> float:
        return float(np.min([b[col].min() for b in self.iter_blocks() if block_num_rows(b)]))

    def max(self, col: str) -> float:
        return float(np.max([b[col].max() for b in self.iter_blocks() if block_num_rows(b)]))

    def mean(self, col: str) -> float:
        tot, cnt = 0.0, 0
        for b in self.iter_blocks():
            n = block_num_rows(b)
            if n:
                tot += float(b[col].sum())
                cnt += n
        return tot / max(cnt, 1)

    def schema(self) -> Dict[str, Any]:
        for b in self.iter_blocks():
            if block_num_rows(b):
                return {k: (v.dtype, v.shape[1:]) for k, v in b.items()}
        return {}

    def num_blocks(self) -> int:
        return sum(1 for _ in self._iter_output_refs())

    def size_bytes(self) -> int:
        return sum(block_size_bytes(b) for b in self.iter_blocks())

    def materialize(self) -> "Dataset":
        """Execute the plan; result holds concrete block refs."""
        return Dataset(list(self._iter_output_refs()))

    def materialize_block(self) -> Block:
        return block_concat(list(self.iter_blocks()))

    def split(self, n: int, *, locality_hints=None) -> List["Dataset"]:
        """Split into n datasets (reference: dataset.py split for per-worker
        ingest shards)."""
        refs = list(self._iter_output_refs())
        if len(refs) < n:
            whole = block_concat([ray_tpu.get(r) for r in refs])
            rows = block_num_rows(whole)
            idx = np.array_split(np.arange(rows), n)
            return [Dataset([ray_tpu.put(block_take(whole, i))]) for i in idx]
        parts = np.array_split(np.arange(len(refs)), n)
        return [Dataset([refs[i] for i in p]) for p in parts]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None) -> Tuple["Dataset", "Dataset"]:
        whole = self.materialize_block()
        n = block_num_rows(whole)
        idx = np.arange(n)
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        k = int(n * (1 - test_size))
        return (
            Dataset([ray_tpu.put(block_take(whole, idx[:k]))]),
            Dataset([ray_tpu.put(block_take(whole, idx[k:]))]),
        )

    # -- writers ---------------------------------------------------------
    def _write_files(self, path: str, fmt: str) -> List[str]:
        """One file per output block, written by remote tasks (reference:
        Dataset.write_parquet/write_csv)."""
        import os

        os.makedirs(path, exist_ok=True)
        local = _use_local_exec()
        out_refs = []
        for i, r in enumerate(self._iter_output_refs()):
            fpath = os.path.join(path, f"part-{i:05d}.{fmt}")
            if local:
                _write_block_file._function(ray_tpu.get(r), fpath, fmt)
                out_refs.append(fpath)
            else:
                out_refs.append(_write_block_file.remote(r, fpath, fmt))
        return [p if isinstance(p, str) else ray_tpu.get(p) for p in out_refs]

    def write_parquet(self, path: str) -> List[str]:
        return self._write_files(path, "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write_files(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write_files(path, "json")

    def __repr__(self) -> str:
        names = [getattr(op, "name", type(op).__name__) for op in self._ops]
        return f"Dataset(blocks={len(self._source_refs)}, plan={' -> '.join(names) or 'source'})"

    stats = __repr__


class GroupedData:
    """Hash-shuffle groupby: rows hash-partition by key (map tasks), each
    reduce task aggregates its partition's groups — no driver
    materialization (reference: hash-shuffle groupby,
    _internal/gpu_shuffle/hash_shuffle.py re-imagined for CPU blocks)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, agg_fn: Callable[[Block], Dict[str, Any]], suffix: str) -> Dataset:
        key = self._key

        def _part(block: Block, k: int, idx: int) -> List[Block]:
            n = block_num_rows(block)
            if not n:
                return [block] * k
            vals = np.asarray(block[key])
            if vals.dtype.kind in "iub":
                assign = vals.astype(np.int64) % k
            else:
                # stable across processes (PYTHONHASHSEED-independent)
                from pandas.util import hash_array

                assign = (hash_array(vals) % k).astype(np.int64)
            return [block_take(block, np.where(assign == j)[0]) for j in range(k)]

        def _reduce(parts: List[Block]) -> Block:
            merged = block_concat(parts)
            if not block_num_rows(merged):
                return {}
            uniq, inverse = np.unique(merged[key], return_inverse=True)
            rows = []
            for gi, kv in enumerate(uniq):
                grp = block_take(merged, np.where(inverse == gi)[0])
                row = {key: kv}
                row.update(agg_fn(grp))
                rows.append(row)
            return block_from_rows(rows)

        return self._ds._with(_Shuffle(_part, _reduce, f"GroupBy({key})"))

    def count(self) -> Dataset:
        return self._agg(lambda g: {"count()": block_num_rows(g)}, "count")

    def sum(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"sum({col})": g[col].sum()}, "sum")

    def mean(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"mean({col})": g[col].mean()}, "mean")

    def max(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"max({col})": g[col].max()}, "max")

    def min(self, col: str) -> Dataset:
        return self._agg(lambda g: {f"min({col})": g[col].min()}, "min")

    def std(self, col: str, ddof: int = 1) -> Dataset:
        # <= ddof rows: dispersion is UNDEFINED, not zero (matching
        # pandas/numpy NaN semantics — 0.0 would claim perfect
        # certainty from a single sample)
        return self._agg(
            lambda g: {f"std({col})": float(np.std(g[col], ddof=ddof))
                       if block_num_rows(g) > ddof
                       else float("nan")}, "std")

    def aggregate(self, **aggs: Tuple[str, str]) -> Dataset:
        """Multiple named aggregations in ONE shuffle (reference:
        GroupedData.aggregate): ``aggregate(total=("x", "sum"),
        hi=("x", "max"))``."""
        fns = {"sum": lambda a: a.sum(), "mean": lambda a: a.mean(),
               "min": lambda a: a.min(), "max": lambda a: a.max(),
               "count": lambda a: len(a),
               "std": lambda a: float(np.std(a, ddof=1))
               if len(a) > 1 else float("nan")}
        for name, (col, op) in aggs.items():
            if op not in fns:
                raise ValueError(f"unknown aggregation {op!r}")

        def _multi(g: Block) -> Dict[str, Any]:
            return {name: fns[op](g[col])
                    for name, (col, op) in aggs.items()}

        return self._agg(_multi, "agg")


@ray_tpu.remote
def _write_block_file(block: Block, path: str, fmt: str) -> str:
    if fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        pq.write_table(
            pa.table({k: list(v) if v.ndim > 1 else v for k, v in block.items()}),
            path,
        )
    elif fmt in ("csv", "json"):
        import pandas as pd

        df = pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in block.items()})
        if fmt == "csv":
            df.to_csv(path, index=False)
        else:
            df.to_json(path, orient="records", lines=True)
    else:
        raise ValueError(f"unknown format {fmt}")
    return path


def _normalize_concurrency(c) -> Optional[int]:
    """Accept the reference's forms: int, or (min, max) autoscaling tuple
    (we size the pool at the upper bound)."""
    if c is None:
        return None
    if isinstance(c, (tuple, list)):
        return int(max(c))
    return int(c)


def _limit_refs(refs: Iterator[Any], n: int) -> Iterator[Any]:
    remaining = n
    for r in refs:
        if remaining <= 0:
            return
        block = ray_tpu.get(r)
        rows = block_num_rows(block)
        if rows <= remaining:
            remaining -= rows
            yield r
        else:
            yield ray_tpu.put(block_slice(block, 0, remaining))
            remaining = 0


def _use_local_exec() -> bool:
    """Local mode (or no cluster) executes the plan in-process."""
    from ray_tpu._private import worker as wm

    w = wm.global_worker
    if w is None or not w.connected:
        return True
    return getattr(w, "mode", None) == wm.LOCAL_MODE
