"""Dataset creation APIs (reference: python/ray/data/read_api.py).

Sources create blocks eagerly-but-cheaply (refs into the object store);
file formats parallelize one task per file via the normal task layer.
"""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_from_rows
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCK_ROWS = 64 * 1024


def from_items(items: List[Any], *, override_num_blocks: Optional[int] = None) -> Dataset:
    n_blocks = override_num_blocks or max(1, min(len(items) // 1000, 64)) or 1
    chunks = np.array_split(np.arange(len(items)), n_blocks)
    refs = [
        ray_tpu.put(block_from_rows([items[i] for i in c])) for c in chunks if len(c)
    ]
    return Dataset(refs)


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    n_blocks = override_num_blocks or max(1, min(n // DEFAULT_BLOCK_ROWS, 64))
    bounds = np.linspace(0, n, n_blocks + 1, dtype=np.int64)
    refs = [
        ray_tpu.put({"id": np.arange(bounds[i], bounds[i + 1])})
        for i in np.arange(n_blocks)
        if bounds[i + 1] > bounds[i]
    ]
    return Dataset(refs)


def range_tensor(n: int, *, shape=(1,), override_num_blocks: Optional[int] = None) -> Dataset:
    ds = range(n, override_num_blocks=override_num_blocks)

    def _expand(block: Block) -> Block:
        ids = block["id"]
        data = np.broadcast_to(
            ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + tuple(shape)
        ).copy()
        return {"data": data}

    return ds.map_batches(lambda b: _expand(b))


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return Dataset([ray_tpu.put({column: np.asarray(arr)})])


def from_blocks(blocks: List[Block]) -> Dataset:
    return Dataset([ray_tpu.put(b) for b in blocks])


def from_pandas(df) -> Dataset:
    return Dataset([ray_tpu.put({c: np.asarray(df[c]) for c in df.columns})])


def from_arrow(table) -> Dataset:
    return Dataset([ray_tpu.put({c: np.asarray(v) for c, v in table.to_pydict().items()})])


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix or ''}")
            out.extend(sorted(glob_mod.glob(pat, recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    return [p for p in out if os.path.isfile(p)]


@ray_tpu.remote
def _read_file_task(path: str, fmt: str, kwargs: Dict[str, Any]) -> Block:
    if fmt == "parquet":
        import pyarrow.parquet as pq

        t = pq.read_table(path, **kwargs)
        return {c: np.asarray(v) for c, v in t.to_pydict().items()}
    if fmt == "csv":
        import pandas as pd

        df = pd.read_csv(path, **kwargs)
        return {c: np.asarray(df[c]) for c in df.columns}
    if fmt == "json":
        import pandas as pd

        df = pd.read_json(path, lines=kwargs.pop("lines", True), **kwargs)
        return {c: np.asarray(df[c]) for c in df.columns}
    if fmt == "text":
        with open(path) as f:
            return {"text": np.asarray([ln.rstrip("\n") for ln in f])}
    if fmt == "npy":
        return {"data": np.load(path, **kwargs)}
    raise ValueError(f"unknown format {fmt}")


def _read_files(paths, fmt: str, suffix: str, **kwargs) -> Dataset:
    files = _expand_paths(paths, suffix)
    if not files:
        raise FileNotFoundError(f"No files found for {paths!r}")
    from ray_tpu.data.dataset import _use_local_exec

    if _use_local_exec():
        refs = [ray_tpu.put(_read_file_task._function(p, fmt, dict(kwargs))) for p in files]
    else:
        refs = [_read_file_task.remote(p, fmt, dict(kwargs)) for p in files]
    return Dataset(refs)


def read_parquet(paths, **kwargs) -> Dataset:
    return _read_files(paths, "parquet", ".parquet", **kwargs)


def read_csv(paths, **kwargs) -> Dataset:
    return _read_files(paths, "csv", ".csv", **kwargs)


def read_json(paths, **kwargs) -> Dataset:
    return _read_files(paths, "json", ".json", **kwargs)


def read_text(paths, **kwargs) -> Dataset:
    return _read_files(paths, "text", ".txt", **kwargs)


def read_numpy(paths, **kwargs) -> Dataset:
    return _read_files(paths, "npy", ".npy", **kwargs)
