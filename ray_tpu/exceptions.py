"""Public exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception during execution.

    Wraps the original traceback so it surfaces at the ``get()`` callsite,
    like the reference's RayTaskError (python/ray/exceptions.py).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task '{function_name}' failed:\n{traceback_str}")

    def __reduce__(self):
        return (RayTaskError, (self.function_name, self.traceback_str, self.cause))

    def as_instanceof_cause(self) -> Exception:
        """Return an exception that is an instance of the cause's class."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if cause_cls is RayTaskError:
            return self
        try:
            class _cls(RayTaskError, cause_cls):  # type: ignore[misc, valid-type]
                def __init__(self, inner: "RayTaskError"):
                    # cause attributes first so callers can read the
                    # typed payload (e.g. CollectiveRankFailure
                    # .dead_ranks) off the wrapper; the wrapper's own
                    # fields win on collision
                    self.__dict__.update(inner.cause.__dict__)
                    self.__dict__.update(inner.__dict__)
                    Exception.__init__(self, str(inner))

            _cls.__name__ = f"RayTaskError({cause_cls.__name__})"
            _cls.__qualname__ = _cls.__name__
            return _cls(self)
        except TypeError:
            return self


class RayActorError(RayTpuError):
    """The actor died before or during method execution."""

    def __init__(self, message: str = "The actor died unexpectedly before finishing this task."):
        super().__init__(message)


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class TaskCancelledError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    """An object was lost (all copies evicted / node died) and could not be
    reconstructed from lineage."""


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class ObjectStoreFullError(RayTpuError):
    pass


class PlacementGroupSchedulingError(RayTpuError):
    pass
