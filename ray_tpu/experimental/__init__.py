"""ray_tpu.experimental — channels (mutable shared-memory objects) and
other pre-stable APIs (reference: python/ray/experimental/)."""

from ray_tpu.experimental.channel import (
    Channel,
    ChannelReader,
    ChannelTimeoutError,
    ChunkPipe,
    ChunkPipeReader,
    TensorChannel,
    TensorChannelReader,
)

__all__ = [
    "Channel",
    "ChannelReader",
    "ChannelTimeoutError",
    "ChunkPipe",
    "ChunkPipeReader",
    "TensorChannel",
    "TensorChannelReader",
]
