"""Mutable-object channels — fixed shared-memory slots rewritten in
place for repeated host-side transfers.

Reference: src/ray/core_worker/experimental_mutable_object_manager.h:44
and python/ray/experimental/channel/shared_memory_channel.py — the
compiled-DAG transport. A channel is ONE shm buffer with a seqlock
header; the writer overwrites the slot each iteration and readers
acquire/release by sequence number, so steady-state transfer does no
allocation, no socket round-trip, and no object-store bookkeeping.

Layout: [seq u64][len u64][ack_0 u64 ... ack_{R-1} u64][payload].
Classic seqlock shape: seq is EVEN when the slot is stable, ODD while
a write is in progress; each write advances it by 2. Write protocol:
wait until every reader's ack == seq (previous value consumed) →
seq+1 (odd) → write len + payload → seq+2 (even). Read protocol: wait
until an even seq > last seen → copy payload → re-read seq; if it
moved, the copy may be torn — retry → store ack = seq.

Honesty note on memory ordering: CPython exposes no fences, so the
re-check narrows but cannot fully close the weak-ordering window (a
reader could in principle observe the even seq before the payload
stores on e.g. ARM). On x86-TSO the store order plus the re-check make
torn reads impossible; full portability would need real atomics in a
C extension.

Endpoints pickle by shm name, so channels pass through task args to
actors on the same node (host-local, like the reference's shm channels;
cross-node channels go through the object store instead).
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_U64 = struct.Struct("<Q")


class ChannelTimeoutError(TimeoutError):
    pass


# segments created by THIS process (tracker-registered on purpose)
_created_here: set = set()


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach (not create) a named segment. Attaching registers the
    segment with THIS process's resource_tracker, which unlinks it when
    the process exits (cpython#82300) — a killed reader would destroy a
    segment the writer and other readers still use. Only the creating
    endpoint may unlink, so deregister the attach (unless this process
    IS the creator — e.g. a driver opening readers on its own channel —
    where deregistering would orphan the creator's registration)."""
    shm = shared_memory.SharedMemory(name=name)
    if shm._name not in _created_here:
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001
            pass
    return shm


class _Endpoint:
    def __init__(self, name: str, capacity: int, num_readers: int,
                 create: bool):
        self.name = name
        self.capacity = capacity
        self.num_readers = num_readers
        self._hdr = 16 + 8 * num_readers
        if create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=self._hdr + capacity)
            _created_here.add(self._shm._name)
            self._shm.buf[: self._hdr] = b"\x00" * self._hdr
        else:
            self._shm = _attach_shm(name)
        self._owner = create
        # u64 view over the header: ~3x faster than struct.unpack_from
        # per access, and the seqlock protocol reads the header in every
        # spin iteration
        self._hu = self._shm.buf[: self._hdr].cast("Q")

    # -- header accessors (word-indexed) --------------------------------
    def _get(self, off: int) -> int:
        return self._hu[off >> 3]

    def _put(self, off: int, v: int) -> None:
        self._hu[off >> 3] = v

    @property
    def _seq(self) -> int:
        return self._hu[0]

    def _release_views(self) -> None:
        """Drop cached views of the mapping so shm.close() can succeed
        (exported pointers block the munmap)."""
        hu, self._hu = self._hu, None
        if hu is not None:
            try:
                hu.release()
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        try:
            self._release_views()
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def _tensor_nbytes(shape, dtype) -> int:
    import numpy as np

    return int(np.dtype(dtype).itemsize * max(1, int(np.prod(tuple(shape)))))


class ChannelReader(_Endpoint):
    """One reader endpoint (index < num_readers)."""

    def __init__(self, name: str, capacity: int, num_readers: int,
                 reader_index: int, _create: bool = False):
        super().__init__(name, capacity, num_readers, create=_create)
        self.reader_index = reader_index
        self._last = self._get(16 + 8 * reader_index)

    def _await_next(self, deadline: Optional[float],
                    timeout: Optional[float]) -> int:
        """Spin until a stable (even) sequence newer than the last-read
        one exists. Pure spins first (sub-transfer latency), then
        progressive naps capped at 0.4 ms — on CPU-starved hosts an
        unbounded busy-poll steals the very cycles the writer needs,
        while a high nap cap overshoots fast writers."""
        spins = 0
        nap = 0.0001
        while True:
            seq = self._seq
            if seq > self._last and seq % 2 == 0:
                return seq
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"no write within {timeout}s (seq={seq})")
            spins += 1
            if spins > 50:
                time.sleep(nap)
                nap = min(nap * 2, 0.0004)

    def read(self, timeout: Optional[float] = 10.0) -> Any:
        """Block until the NEXT value is written; acknowledge it."""
        # one deadline for the whole call: the seqlock retry loop must
        # not restart the clock each time a concurrent write invalidates
        # a copy, or the declared timeout stops being an upper bound
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._await_next(deadline, timeout)
            n = self._get(8)
            data = bytes(self._shm.buf[self._hdr: self._hdr + n])
            if self._seq == seq:  # seqlock re-check: no concurrent write
                break
        value = pickle.loads(data)
        self._last = seq
        self._put(16 + 8 * self.reader_index, seq)  # release
        return value

    def __reduce__(self):
        return (ChannelReader, (self.name, self.capacity, self.num_readers,
                                self.reader_index))


class Channel(_Endpoint):
    """Writer endpoint; create once, ``write()`` per iteration.

    num_readers readers must each ``read()`` every value before the next
    write proceeds (the reference's acquire/release backpressure).
    """

    def __init__(self, capacity: int = 1 << 20, num_readers: int = 1,
                 name: Optional[str] = None, _attach: bool = False):
        import uuid

        name = name or f"rtch_{uuid.uuid4().hex[:12]}"
        super().__init__(name, capacity, num_readers,
                         create=not _attach)

    def _await_acks(self, seq: int, timeout: Optional[float]) -> None:
        """Spin until every reader consumed the previous value (same
        spin-then-nap rationale as ChannelReader._await_next)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        nap = 0.0001
        while any(self._get(16 + 8 * i) < seq
                  for i in range(self.num_readers)):
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"readers did not consume value {seq} within {timeout}s")
            spins += 1
            if spins > 50:
                time.sleep(nap)
                nap = min(nap * 2, 0.0004)

    def write(self, value: Any, timeout: Optional[float] = 10.0) -> None:
        data = pickle.dumps(value, protocol=5)
        if len(data) > self.capacity:
            raise ValueError(
                f"value of {len(data)}B exceeds channel capacity "
                f"{self.capacity}B")
        seq = self._seq
        self._await_acks(seq, timeout)
        self._put(0, seq + 1)  # odd: write in progress
        self._shm.buf[self._hdr: self._hdr + len(data)] = data
        self._put(8, len(data))
        self._put(0, seq + 2)  # even: release

    def reader(self, reader_index: int = 0) -> ChannelReader:
        if not 0 <= reader_index < self.num_readers:
            raise ValueError(
                f"reader_index {reader_index} out of range "
                f"(num_readers={self.num_readers})")
        return ChannelReader(self.name, self.capacity, self.num_readers,
                             reader_index)

    def __reduce__(self):
        # an unpickled writer endpoint attaches (does not re-create/own)
        return (Channel, (self.capacity, self.num_readers, self.name, True))


# ---------------------------------------------------------------------------
# Typed tensor channels — the RDT host path (reference:
# python/ray/experimental/rdt/ — tensor transports bypassing the object
# store). Fixed shape+dtype means the payload is written as raw array
# bytes straight into shared memory: no pickling, no allocation per
# transfer. The device path needs no transport at all on TPU — arrays
# move with jax.device_put / inside jitted collectives over ICI.
# ---------------------------------------------------------------------------
class TensorChannelReader(ChannelReader):
    def __init__(self, name: str, shape, dtype: str, num_readers: int,
                 reader_index: int):
        import numpy as np

        self.shape = tuple(shape)
        self.dtype = dtype
        super().__init__(name, _tensor_nbytes(shape, dtype), num_readers,
                         reader_index)
        # the slot view is position-independent: build it once, not per read
        self._slot = np.ndarray(self.shape, self.dtype,
                                buffer=self._shm.buf, offset=self._hdr)
        self._borrowed = False

    def read(self, timeout: Optional[float] = 10.0):
        """Returns a fresh ndarray (copied out of the slot — the writer
        reuses it immediately after the ack)."""
        import numpy as np

        self._end_borrow()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._await_next(deadline, timeout)
            out = np.copy(self._slot)
            if self._seq == seq:  # seqlock re-check: no concurrent write
                break
        self._last = seq
        self._put(16 + 8 * self.reader_index, seq)
        return out

    def read_view(self, timeout: Optional[float] = 10.0):
        """Zero-copy borrowed read: returns a READ-ONLY view of the slot
        itself. The view is valid until ``release()`` (or the next
        read/read_view, which releases implicitly); the writer cannot
        overwrite the slot while the borrow is outstanding because the
        ack is withheld. This is the copy-free consumption path the
        pipelined collectives use (reduce directly out of shared memory);
        ``read()`` remains the safe owning-copy default."""
        self._end_borrow()
        deadline = None if timeout is None else time.monotonic() + timeout
        seq = self._await_next(deadline, timeout)
        # no re-check needed: the writer blocks on our ack before the
        # next write, so the slot is stable until release()
        self._last = seq
        self._borrowed = True
        view = self._slot.view()
        view.flags.writeable = False
        return view

    def release(self) -> None:
        """Ack the borrowed slot from read_view(), letting the writer
        reuse it. The borrowed view must no longer be read."""
        self._end_borrow()

    def _end_borrow(self) -> None:
        if self._borrowed:
            self._borrowed = False
            self._put(16 + 8 * self.reader_index, self._last)

    def close(self) -> None:
        self._end_borrow()  # ack an outstanding read_view borrow
        self._slot = None
        super().close()

    def __reduce__(self):
        return (TensorChannelReader, (self.name, self.shape, self.dtype,
                                      self.num_readers, self.reader_index))


class TensorChannel(Channel):
    """Zero-copy fixed-shape tensor slot: ``write`` copies array bytes
    directly into shared memory (no pickle)."""

    def __init__(self, shape, dtype: str = "float32", num_readers: int = 1,
                 name: Optional[str] = None, _attach: bool = False):
        import numpy as np

        self.shape = tuple(shape)
        self.dtype = str(np.dtype(dtype))
        super().__init__(_tensor_nbytes(shape, dtype), num_readers, name,
                         _attach)
        self._slot = np.ndarray(self.shape, self.dtype,
                                buffer=self._shm.buf, offset=self._hdr)

    def write(self, arr, timeout: Optional[float] = 10.0) -> None:
        import numpy as np

        if getattr(arr, "shape", None) != self.shape \
                or str(getattr(arr, "dtype", "")) != self.dtype:
            arr = np.asarray(arr)
            if arr.shape != self.shape or str(arr.dtype) != self.dtype:
                raise ValueError(
                    f"expected {self.shape} {self.dtype}, got "
                    f"{arr.shape} {arr.dtype}")
        seq = self._seq
        self._await_acks(seq, timeout)
        self._put(0, seq + 1)  # odd: write in progress
        # copyto handles non-contiguous sources directly: exactly one
        # payload copy, source array → shared memory
        np.copyto(self._slot, arr)
        self._put(8, self._slot.nbytes)
        self._put(0, seq + 2)  # even: release

    def close(self) -> None:
        self._slot = None
        super().close()

    def reader(self, reader_index: int = 0) -> TensorChannelReader:
        if not 0 <= reader_index < self.num_readers:
            raise ValueError(
                f"reader_index {reader_index} out of range "
                f"(num_readers={self.num_readers})")
        return TensorChannelReader(self.name, self.shape, self.dtype,
                                   self.num_readers, reader_index)

    def __reduce__(self):
        return (TensorChannel, (self.shape, self.dtype, self.num_readers,
                                self.name, True))


# ---------------------------------------------------------------------------
# ChunkPipe — double-buffered byte-chunk transport for PIPELINED
# collectives. A pipe is ``num_slots`` independent seqlock slots of
# ``chunk_bytes`` each in one shm segment; the writer round-robins the
# slots, so chunk k+1 is in flight while the consumer still reduces
# chunk k straight out of slot k (transport/compute overlap with zero
# reader-side copies). Shape-independent: one pipe per ring edge serves
# every tensor the group ever reduces.
# ---------------------------------------------------------------------------
_SLOT_HDR = 24  # [seq u64][len u64][ack u64] — single reader per pipe


class _PipeBase:
    def __init__(self, name: str, chunk_bytes: int, num_slots: int,
                 create: bool):
        self.name = name
        self.chunk_bytes = chunk_bytes
        self.num_slots = num_slots
        self._stride = _SLOT_HDR + chunk_bytes
        size = self._stride * num_slots
        if create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=size)
            _created_here.add(self._shm._name)
            # fresh POSIX shm is zero-filled by ftruncate; zero only the
            # slot headers defensively (multi-MiB payload memset wasted)
            for i in range(num_slots):
                off = i * self._stride
                self._shm.buf[off: off + _SLOT_HDR] = b"\x00" * _SLOT_HDR
        else:
            self._shm = _attach_shm(name)
        self._owner = create
        # one u64 header view per slot (cast views beat struct.unpack
        # in the spin loops), plus one payload view per slot
        self._hu = [
            self._shm.buf[i * self._stride: i * self._stride + _SLOT_HDR
                          ].cast("Q")
            for i in range(num_slots)
        ]
        self._payload = [
            self._shm.buf[i * self._stride + _SLOT_HDR:
                          (i + 1) * self._stride]
            for i in range(num_slots)
        ]
        self._count = 0  # monotonically increasing chunk counter

    @staticmethod
    def _spin(cond, deadline: Optional[float], what: str):
        """Pipe waits are SHORT (a peer's chunk memcpy, tens to hundreds
        of µs): spin, then yield the core (sched_yield keeps the peer
        process fed on oversubscribed hosts), then short capped naps —
        the 0.4 ms naps of the generic channels overshoot every chunk
        and halve delivered pipeline bandwidth."""
        spins = 0
        nap = 0.00005
        while not cond():
            spins += 1
            if spins > 4000:
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(what)
                time.sleep(nap)
                nap = min(nap * 2, 0.0002)
            elif spins % 200 == 0:
                time.sleep(0)  # yield to the peer on a saturated host
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(what)

    def close(self) -> None:
        try:
            views, self._hu, self._payload = \
                (self._hu or []) + (self._payload or []), None, None
            for v in views:
                try:
                    v.release()
                except Exception:  # noqa: BLE001
                    pass
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class ChunkPipe(_PipeBase):
    """Writer endpoint. ``write_chunk`` blocks only when every slot is
    still un-acked — with the default two slots the transport of one
    chunk overlaps the consumer's reduce of the previous one."""

    def __init__(self, chunk_bytes: int, num_slots: int = 2,
                 name: Optional[str] = None, _attach: bool = False):
        import uuid

        name = name or f"rtpipe_{uuid.uuid4().hex[:12]}"
        super().__init__(name, chunk_bytes, num_slots, create=not _attach)

    def write_chunk(self, data, timeout: Optional[float] = 10.0) -> None:
        """Copy ``data`` (buffer-protocol, <= chunk_bytes) into the next
        slot; exactly one payload copy, source → shared memory."""
        mv = data if isinstance(data, memoryview) else memoryview(data)
        if mv.nbytes > self.chunk_bytes:
            raise ValueError(
                f"chunk of {mv.nbytes}B exceeds pipe chunk size "
                f"{self.chunk_bytes}B")
        slot = self._count % self.num_slots
        h = self._hu[slot]
        seq = h[0]
        deadline = None if timeout is None else time.monotonic() + timeout
        # previous value in this slot must be consumed (ack == seq)
        self._spin(lambda: h[2] >= seq, deadline,
                   f"pipe reader did not consume slot {slot} "
                   f"within {timeout}s")
        h[0] = seq + 1  # odd: write in progress
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        self._payload[slot][: mv.nbytes] = mv
        h[1] = mv.nbytes
        h[0] = seq + 2  # even: release
        self._count += 1

    def __reduce__(self):
        return (ChunkPipe, (self.chunk_bytes, self.num_slots, self.name,
                            True))


class ChunkPipeReader(_PipeBase):
    """Reader endpoint; strict borrow discipline:

        view = r.next_chunk()   # zero-copy view of the slot payload
        ... consume (reduce/copy out of shared memory) ...
        r.release_chunk()       # ack — the writer may now reuse the slot
    """

    def __init__(self, name: str, chunk_bytes: int, num_slots: int = 2):
        super().__init__(name, chunk_bytes, num_slots, create=False)
        self._borrowed: Optional[int] = None

    def next_chunk(self, timeout: Optional[float] = 10.0) -> memoryview:
        assert self._borrowed is None, "previous chunk not released"
        slot = self._count % self.num_slots
        h = self._hu[slot]
        last = self._last_seq(slot)
        deadline = None if timeout is None else time.monotonic() + timeout
        self._spin(lambda: h[0] > last and h[0] % 2 == 0, deadline,
                   f"no chunk in slot {slot} within {timeout}s")
        self._borrowed = slot
        return self._payload[slot][: h[1]]

    def _last_seq(self, slot: int) -> int:
        # the ack we last published for this slot IS the last seq consumed
        return self._hu[slot][2]

    def release_chunk(self) -> None:
        slot, self._borrowed = self._borrowed, None
        if slot is not None:
            h = self._hu[slot]
            h[2] = h[0]  # ack the seq we just consumed
            self._count += 1

    def __reduce__(self):
        return (ChunkPipeReader, (self.name, self.chunk_bytes,
                                  self.num_slots))
