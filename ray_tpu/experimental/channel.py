"""Mutable-object channels — fixed shared-memory slots rewritten in
place for repeated host-side transfers.

Reference: src/ray/core_worker/experimental_mutable_object_manager.h:44
and python/ray/experimental/channel/shared_memory_channel.py — the
compiled-DAG transport. A channel is ONE shm buffer with a seqlock
header; the writer overwrites the slot each iteration and readers
acquire/release by sequence number, so steady-state transfer does no
allocation, no socket round-trip, and no object-store bookkeeping.

Layout: [seq u64][len u64][ack_0 u64 ... ack_{R-1} u64][payload].
Classic seqlock shape: seq is EVEN when the slot is stable, ODD while
a write is in progress; each write advances it by 2. Write protocol:
wait until every reader's ack == seq (previous value consumed) →
seq+1 (odd) → write len + payload → seq+2 (even). Read protocol: wait
until an even seq > last seen → copy payload → re-read seq; if it
moved, the copy may be torn — retry → store ack = seq.

Honesty note on memory ordering: CPython exposes no fences, so the
re-check narrows but cannot fully close the weak-ordering window (a
reader could in principle observe the even seq before the payload
stores on e.g. ARM). On x86-TSO the store order plus the re-check make
torn reads impossible; full portability would need real atomics in a
C extension.

Endpoints pickle by shm name, so channels pass through task args to
actors on the same node (host-local, like the reference's shm channels;
cross-node channels go through the object store instead).
"""

from __future__ import annotations

import pickle
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Optional

_U64 = struct.Struct("<Q")


class ChannelTimeoutError(TimeoutError):
    pass


class _Endpoint:
    def __init__(self, name: str, capacity: int, num_readers: int,
                 create: bool):
        self.name = name
        self.capacity = capacity
        self.num_readers = num_readers
        self._hdr = 16 + 8 * num_readers
        if create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=self._hdr + capacity)
            self._shm.buf[: self._hdr] = b"\x00" * self._hdr
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        self._owner = create

    # -- header accessors ----------------------------------------------
    def _get(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _put(self, off: int, v: int) -> None:
        _U64.pack_into(self._shm.buf, off, v)

    @property
    def _seq(self) -> int:
        return self._get(0)

    def close(self) -> None:
        try:
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # noqa: BLE001
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def _tensor_nbytes(shape, dtype) -> int:
    import numpy as np

    return int(np.dtype(dtype).itemsize * max(1, int(np.prod(tuple(shape)))))


class ChannelReader(_Endpoint):
    """One reader endpoint (index < num_readers)."""

    def __init__(self, name: str, capacity: int, num_readers: int,
                 reader_index: int, _create: bool = False):
        super().__init__(name, capacity, num_readers, create=_create)
        self.reader_index = reader_index
        self._last = self._get(16 + 8 * reader_index)

    def _await_next(self, deadline: Optional[float],
                    timeout: Optional[float]) -> int:
        """Spin until a stable (even) sequence newer than the last-read
        one exists. Pure spins first (sub-transfer latency), then
        progressive naps capped at 0.4 ms — on CPU-starved hosts an
        unbounded busy-poll steals the very cycles the writer needs,
        while a high nap cap overshoots fast writers."""
        spins = 0
        nap = 0.0001
        while True:
            seq = self._seq
            if seq > self._last and seq % 2 == 0:
                return seq
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"no write within {timeout}s (seq={seq})")
            spins += 1
            if spins > 50:
                time.sleep(nap)
                nap = min(nap * 2, 0.0004)

    def read(self, timeout: Optional[float] = 10.0) -> Any:
        """Block until the NEXT value is written; acknowledge it."""
        # one deadline for the whole call: the seqlock retry loop must
        # not restart the clock each time a concurrent write invalidates
        # a copy, or the declared timeout stops being an upper bound
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._await_next(deadline, timeout)
            n = self._get(8)
            data = bytes(self._shm.buf[self._hdr: self._hdr + n])
            if self._seq == seq:  # seqlock re-check: no concurrent write
                break
        value = pickle.loads(data)
        self._last = seq
        self._put(16 + 8 * self.reader_index, seq)  # release
        return value

    def __reduce__(self):
        return (ChannelReader, (self.name, self.capacity, self.num_readers,
                                self.reader_index))


class Channel(_Endpoint):
    """Writer endpoint; create once, ``write()`` per iteration.

    num_readers readers must each ``read()`` every value before the next
    write proceeds (the reference's acquire/release backpressure).
    """

    def __init__(self, capacity: int = 1 << 20, num_readers: int = 1,
                 name: Optional[str] = None, _attach: bool = False):
        import uuid

        name = name or f"rtch_{uuid.uuid4().hex[:12]}"
        super().__init__(name, capacity, num_readers,
                         create=not _attach)

    def _await_acks(self, seq: int, timeout: Optional[float]) -> None:
        """Spin until every reader consumed the previous value (same
        spin-then-nap rationale as ChannelReader._await_next)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        nap = 0.0001
        while any(self._get(16 + 8 * i) < seq
                  for i in range(self.num_readers)):
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"readers did not consume value {seq} within {timeout}s")
            spins += 1
            if spins > 50:
                time.sleep(nap)
                nap = min(nap * 2, 0.0004)

    def write(self, value: Any, timeout: Optional[float] = 10.0) -> None:
        data = pickle.dumps(value, protocol=5)
        if len(data) > self.capacity:
            raise ValueError(
                f"value of {len(data)}B exceeds channel capacity "
                f"{self.capacity}B")
        seq = self._seq
        self._await_acks(seq, timeout)
        self._put(0, seq + 1)  # odd: write in progress
        self._shm.buf[self._hdr: self._hdr + len(data)] = data
        self._put(8, len(data))
        self._put(0, seq + 2)  # even: release

    def reader(self, reader_index: int = 0) -> ChannelReader:
        if not 0 <= reader_index < self.num_readers:
            raise ValueError(
                f"reader_index {reader_index} out of range "
                f"(num_readers={self.num_readers})")
        return ChannelReader(self.name, self.capacity, self.num_readers,
                             reader_index)

    def __reduce__(self):
        # an unpickled writer endpoint attaches (does not re-create/own)
        return (Channel, (self.capacity, self.num_readers, self.name, True))


# ---------------------------------------------------------------------------
# Typed tensor channels — the RDT host path (reference:
# python/ray/experimental/rdt/ — tensor transports bypassing the object
# store). Fixed shape+dtype means the payload is written as raw array
# bytes straight into shared memory: no pickling, no allocation per
# transfer. The device path needs no transport at all on TPU — arrays
# move with jax.device_put / inside jitted collectives over ICI.
# ---------------------------------------------------------------------------
class TensorChannelReader(ChannelReader):
    def __init__(self, name: str, shape, dtype: str, num_readers: int,
                 reader_index: int):
        self.shape = tuple(shape)
        self.dtype = dtype
        super().__init__(name, _tensor_nbytes(shape, dtype), num_readers,
                         reader_index)

    def read(self, timeout: Optional[float] = 10.0):
        """Returns a fresh ndarray (copied out of the slot — the writer
        reuses it immediately after the ack)."""
        import numpy as np

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._await_next(deadline, timeout)
            view = np.ndarray(self.shape, self.dtype,
                              buffer=self._shm.buf, offset=self._hdr)
            out = view.copy()
            if self._seq == seq:  # seqlock re-check: no concurrent write
                break
        self._last = seq
        self._put(16 + 8 * self.reader_index, seq)
        return out

    def __reduce__(self):
        return (TensorChannelReader, (self.name, self.shape, self.dtype,
                                      self.num_readers, self.reader_index))


class TensorChannel(Channel):
    """Zero-copy fixed-shape tensor slot: ``write`` copies array bytes
    directly into shared memory (no pickle)."""

    def __init__(self, shape, dtype: str = "float32", num_readers: int = 1,
                 name: Optional[str] = None, _attach: bool = False):
        import numpy as np

        self.shape = tuple(shape)
        self.dtype = str(np.dtype(dtype))
        super().__init__(_tensor_nbytes(shape, dtype), num_readers, name,
                         _attach)

    def write(self, arr, timeout: Optional[float] = 10.0) -> None:
        import numpy as np

        arr = np.ascontiguousarray(arr)
        if arr.shape != self.shape or str(arr.dtype) != self.dtype:
            raise ValueError(
                f"expected {self.shape} {self.dtype}, got "
                f"{arr.shape} {arr.dtype}")
        seq = self._seq
        self._await_acks(seq, timeout)
        self._put(0, seq + 1)  # odd: write in progress
        dest = np.ndarray(self.shape, self.dtype,
                          buffer=self._shm.buf, offset=self._hdr)
        dest[...] = arr
        self._put(8, arr.nbytes)
        self._put(0, seq + 2)  # even: release

    def reader(self, reader_index: int = 0) -> TensorChannelReader:
        if not 0 <= reader_index < self.num_readers:
            raise ValueError(
                f"reader_index {reader_index} out of range "
                f"(num_readers={self.num_readers})")
        return TensorChannelReader(self.name, self.shape, self.dtype,
                                   self.num_readers, reader_index)

    def __reduce__(self):
        return (TensorChannel, (self.shape, self.dtype, self.num_readers,
                                self.name, True))
