"""RDT — direct tensor transport between actors, device-aware.

Reference: python/ray/experimental/rdt/collective_tensor_transport.py:34
and nixl_tensor_transport.py:94 — the reference moves GPU tensors
actor-to-actor through NCCL collectives or NIXL, bypassing the object
store's pickle path. The TPU equivalents, in preference order:

1. **In-jit collectives** — tensors that move between devices as part
   of a sharded computation never leave XLA: ``psum``/``ppermute``
   over ICI (ray_tpu.util.collective / parallel.*). That is the real
   TPU device path and needs no transport object at all.
2. **Same-host, cross-process** (this module): a shared-memory
   ``DeviceTensorChannel`` — the producer's device array is DMA'd to a
   pinned host buffer and memcpy'd into shm (no pickle), the consumer
   maps the same shm and ``jax.device_put``s onto its device. bfloat16
   rides as a uint16 view (numpy has no bf16 wire type).
3. **Cross-host**: the chunked object-store pull path (already
   pickle-free for array payloads via pickle-5 out-of-band buffers) —
   on real pods, prefer (1): DCN-routed XLA collectives.

``DeviceTensorChannel`` keeps the typed channels' fixed-shape seqlock
protocol, so hand-off cost is one D2H + one memcpy + one H2D, with
backpressure from the reader ack.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ray_tpu.experimental.channel import TensorChannel, TensorChannelReader

_BF16_WIRE = "uint16"  # numpy-safe carrier for bfloat16 payloads


def _wire_dtype(dtype_str: str) -> Tuple[str, bool]:
    if dtype_str == "bfloat16":
        return _BF16_WIRE, True
    return dtype_str, False


def _to_host(arr) -> np.ndarray:
    """Device array -> contiguous host ndarray without pickle. For jax
    arrays this is the runtime's D2H DMA; numpy passes through."""
    try:
        import jax

        if isinstance(arr, jax.Array):
            arr = np.asarray(arr)
    except Exception:  # noqa: BLE001 — jax absent: numpy-only mode
        pass
    return np.ascontiguousarray(arr)


class DeviceTensorChannel:
    """Fixed-shape device-tensor slot between two local actors."""

    def __init__(self, shape, dtype: str = "float32",
                 num_readers: int = 1, name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = str(dtype)
        wire, self._is_bf16 = _wire_dtype(self.dtype)
        self._ch = TensorChannel(shape, wire, num_readers=num_readers,
                                 name=name)
        self.name = self._ch.name

    def write(self, arr, timeout: Optional[float] = 10.0) -> None:
        host = _to_host(arr)
        if self._is_bf16:
            if str(host.dtype) != "bfloat16":
                raise ValueError(
                    f"channel carries bfloat16, got {host.dtype}")
            host = host.view(np.uint16)
        self._ch.write(host, timeout=timeout)

    def reader(self, reader_index: int = 0,
               device: Any = None) -> "DeviceTensorReader":
        return DeviceTensorReader(self.name, self.shape, self.dtype,
                                  self._ch.num_readers, reader_index,
                                  device)

    def close(self) -> None:
        self._ch.close()

    def __reduce__(self):
        return (_rebuild_channel, (self.name, self.shape, self.dtype,
                                   self._ch.num_readers))


def _rebuild_channel(name, shape, dtype, num_readers):
    ch = DeviceTensorChannel.__new__(DeviceTensorChannel)
    ch.shape = tuple(shape)
    ch.dtype = str(dtype)
    wire, ch._is_bf16 = _wire_dtype(ch.dtype)
    ch._ch = TensorChannel(shape, wire, num_readers=num_readers,
                           name=name, _attach=True)
    ch.name = name
    return ch


class DeviceTensorReader:
    """Reads the shm slot and lands the tensor on a device."""

    def __init__(self, name: str, shape, dtype: str, num_readers: int,
                 reader_index: int, device: Any = None):
        self.shape = tuple(shape)
        self.dtype = str(dtype)
        wire, self._is_bf16 = _wire_dtype(self.dtype)
        self._rd = TensorChannelReader(name, shape, wire, num_readers,
                                       reader_index)
        self.device = device

    def read(self, timeout: Optional[float] = 10.0):
        """Returns a jax.Array on ``device`` (default: the process's
        default device); falls back to numpy when jax is unavailable."""
        host = self._rd.read(timeout=timeout)
        if self._is_bf16:
            from ml_dtypes import bfloat16 as _bf16

            host = host.view(_bf16)
        try:
            import jax

            dev = self.device or jax.devices()[0]
            return jax.device_put(host, dev)
        except ImportError:
            return host

    def __reduce__(self):
        return (DeviceTensorReader, (self._rd.name, self.shape,
                                     self.dtype, self._rd.num_readers,
                                     self._rd.reader_index, None))
