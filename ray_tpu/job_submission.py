"""ray_tpu.job_submission — reference-parity alias for the job API
(reference: `ray.job_submission` re-exporting the dashboard SDK,
python/ray/job_submission/__init__.py)."""

from ray_tpu.dashboard.job_client import JobSubmissionClient


class JobStatus:
    """String states (reference: job_submission JobStatus enum)."""

    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


__all__ = ["JobStatus", "JobSubmissionClient"]
