"""ray_tpu.llm — LLM batch inference and serving (reference: python/ray/llm).

The reference wraps vLLM (`python/ray/llm/_internal/batch/`, `_internal/
serve/engines/vllm/`); here the engine is JAX-native: KV-cache prefill +
decode over the flagship transformer (ray_tpu/models/decoding.py), so
generation compiles to two XLA programs (prefill, per-token decode) and
runs on the TPU MXU.

- ``LLMConfig`` — model + generation + deployment settings
- ``LLMEngine`` — in-process generator (tokenize → generate → detokenize)
- ``build_llm_processor`` — batch inference over ray_tpu.data Datasets
- ``build_llm_deployment`` / ``serve_llm`` — a Serve deployment with
  request batching and streaming token responses
"""

from ray_tpu.llm.config import ByteTokenizer, LLMConfig
from ray_tpu.llm.engine import LLMEngine
from ray_tpu.llm.batch import build_llm_processor
from ray_tpu.llm.serving import build_llm_deployment, serve_llm

from ray_tpu.models.decoding import Generator, SamplingParams

__all__ = [
    "ByteTokenizer",
    "Generator",
    "LLMConfig",
    "LLMEngine",
    "SamplingParams",
    "build_llm_deployment",
    "build_llm_processor",
    "serve_llm",
]
