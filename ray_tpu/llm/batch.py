"""LLM batch inference over ray_tpu.data (reference:
python/ray/llm/_internal/batch/processor/ — vLLM engine processors).

``build_llm_processor(config)`` returns ``Dataset -> Dataset``: each
data-worker process lazily builds ONE engine (cached per config) and
maps prompt batches through it, so generation parallelism follows the
Data executor's task parallelism and blocks stream (no full
materialization on the driver).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.llm.config import LLMConfig
from ray_tpu.models.decoding import SamplingParams

# one engine per (worker process, config identity) — map_batches fns run
# in data-executor worker processes; rebuilding the engine per block
# would recompile prefill/decode every time
_ENGINE_CACHE: Dict[tuple, Any] = {}


def _engine_for(config: LLMConfig):
    # stable across pickling into data-worker processes (id() is not);
    # class name alone can't distinguish two HF tokenizers, so include
    # their vocab/name attributes too
    tok = config.get_tokenizer()
    key = (str(config.model), config.max_len, config.params_path,
           config.seed, type(tok).__name__,
           getattr(tok, "vocab_size", None),
           str(getattr(tok, "name_or_path", None)))
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        from ray_tpu.llm.engine import LLMEngine

        eng = LLMEngine(config)
        _ENGINE_CACHE[key] = eng
    return eng


def build_llm_processor(
    config: LLMConfig,
    *,
    sampling: Optional[SamplingParams] = None,
    prompt_column: str = "prompt",
    output_column: str = "generated",
    batch_size: Optional[int] = None,
) -> Callable:
    """Returns ``process(ds) -> ds`` adding ``output_column`` with the
    completion for each row's ``prompt_column``."""

    def _infer(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        eng = _engine_for(config)
        prompts = [str(p) for p in batch[prompt_column]]
        outs = eng.generate(prompts, sampling)
        return dict(batch, **{output_column: np.asarray(outs, object)})

    def process(ds):
        return ds.map_batches(_infer, batch_size=batch_size)

    return process
