"""LLM configuration + tokenizer protocol.

Reference surface: ray.llm LLMConfig (python/ray/llm/_internal/serve/
configs/server_models.py) — model id + engine + deployment settings in
one object. The tokenizer is pluggable: anything with encode/decode
(e.g. a transformers tokenizer) works; ByteTokenizer is the dependency-
free default so the stack runs hermetically in tests.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from ray_tpu.models.decoding import SamplingParams


class ByteTokenizer:
    """UTF-8 bytes as token ids (0-255); id 256 = EOS.

    Hermetic default — real deployments pass a transformers tokenizer.
    """

    vocab_size = 257
    eos_token_id = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace")


@dataclasses.dataclass
class LLMConfig:
    """Model + generation + deployment settings (reference:
    ray.llm LLMConfig)."""

    model: Any = "tiny"  # preset name or TransformerConfig
    max_len: int = 512
    params_path: Optional[str] = None  # orbax checkpoint dir (else random init)
    tokenizer: Any = None  # encode/decode object; default ByteTokenizer
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    seed: int = 0
    # serve-side deployment settings
    name: str = "llm"
    num_replicas: int = 1
    batch_max_size: int = 8
    batch_wait_timeout_s: float = 0.05
    resources: Optional[dict] = None  # e.g. {"TPU": 1}
    # iteration-level scheduling over a fixed-slot KV cache (vLLM-style);
    # False falls back to @serve.batch whole-batch generation
    continuous_batching: bool = True
    cache_slots: int = 8

    def get_tokenizer(self):
        return self.tokenizer if self.tokenizer is not None else ByteTokenizer()
