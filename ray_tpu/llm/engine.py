"""In-process LLM engine: tokenizer + compiled generator.

Reference: the vLLM engine wrapper (python/ray/llm/_internal/serve/
engines/vllm/vllm_engine.py) — ours drives ray_tpu.models.decoding.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.llm.config import LLMConfig
from ray_tpu.models.decoding import Generator, SamplingParams


class LLMEngine:
    def __init__(self, config: LLMConfig):
        import jax

        from ray_tpu.models import transformer as T

        self.config = config
        self.tokenizer = config.get_tokenizer()
        cfg = T.config(config.model)
        vocab = getattr(self.tokenizer, "vocab_size", None)
        if vocab and vocab > cfg.vocab_size:
            # model must cover the tokenizer's id space
            cfg = T.config(cfg, vocab_size=int(vocab))
        self.model_config = cfg
        if config.params_path:
            from ray_tpu.train.checkpoint import restore_state

            params_shape = jax.eval_shape(
                lambda: T.init_params(cfg, jax.random.key(0)))
            params = restore_state(config.params_path, target=params_shape)
        else:
            params = T.init_params(cfg, jax.random.key(config.seed))
        self.generator = Generator(cfg, params, max_len=config.max_len)
        self._call_count = 0

    def next_seed(self) -> int:
        """Fresh seed per call: temperature sampling must differ across
        requests for the same prompt (deterministic given config.seed
        and call order, so tests stay reproducible)."""
        self._call_count += 1
        return self.config.seed + self._call_count

    def generate_tokens(self, prompts: Sequence[Sequence[int]],
                        sampling: Optional[SamplingParams] = None
                        ) -> List[List[int]]:
        sampling = sampling or self.config.sampling
        return self.generator.generate(
            [list(p) for p in prompts], sampling, seed=self.next_seed())

    def _with_eos(self, sampling: SamplingParams) -> SamplingParams:
        tok = self.tokenizer
        if sampling.stop_token_id is None and \
                getattr(tok, "eos_token_id", None) is not None:
            import dataclasses

            sampling = dataclasses.replace(
                sampling, stop_token_id=tok.eos_token_id)
        return sampling

    def generate(self, prompts: Sequence[Union[str, Sequence[int]]],
                 sampling: Optional[SamplingParams] = None) -> List[str]:
        """Text in → text out (token-id prompts pass through encode)."""
        tok = self.tokenizer
        sampling = self._with_eos(sampling or self.config.sampling)
        ids = [tok.encode(p) if isinstance(p, str) else list(p)
               for p in prompts]
        # empty prompts would index position -1 at prefill; give them BOS=0
        ids = [p if p else [0] for p in ids]
        outs = self.generate_tokens(ids, sampling)
        return [tok.decode(o) for o in outs]


class ContinuousLLMEngine(LLMEngine):
    """Engine whose device loop is a ContinuousBatcher: concurrent
    callers share decode steps, new requests join the running batch the
    moment a slot frees (reference: vLLM iteration-level scheduling —
    models/continuous_batching.py is the TPU-native core)."""

    def __init__(self, config: LLMConfig):
        super().__init__(config)
        from ray_tpu.models.continuous_batching import ContinuousBatcher

        self.batcher = ContinuousBatcher(
            self.model_config, self.generator.params,
            max_len=config.max_len, slots=config.cache_slots,
            seed=config.seed)

    def submit(self, prompt: Union[str, Sequence[int]],
               sampling: Optional[SamplingParams] = None):
        """Thread-safe; returns a Future resolving to the completion
        TEXT."""
        from concurrent.futures import Future

        tok = self.tokenizer
        sampling = self._with_eos(sampling or self.config.sampling)
        ids = tok.encode(prompt) if isinstance(prompt, str) else list(prompt)
        inner = self.batcher.submit(ids or [0], sampling)
        out: Future = Future()

        def _chain(f):
            # concurrent.futures swallows callback exceptions: a decode
            # failure must still resolve `out` or the caller hangs
            try:
                exc = f.exception()
                if exc is not None:
                    out.set_exception(exc)
                else:
                    # raycheck: disable=RC001 — done-callback: f resolved
                    out.set_result(tok.decode(f.result()))
            except BaseException as e:  # noqa: BLE001
                if not out.done():
                    out.set_exception(e)

        inner.add_done_callback(_chain)
        return out

    def submit_stream(self, prompt: Union[str, Sequence[int]],
                      sampling: Optional[SamplingParams] = None):
        """Yields token ids as the batcher emits them."""
        tok = self.tokenizer
        sampling = self._with_eos(sampling or self.config.sampling)
        ids = tok.encode(prompt) if isinstance(prompt, str) else list(prompt)
        return self.batcher.submit_stream(ids or [0], sampling)

    def shutdown(self) -> None:
        self.batcher.shutdown()
