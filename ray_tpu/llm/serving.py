"""LLM serving on ray_tpu.serve (reference: python/ray/llm/_internal/
serve/ — LLM deployments over vLLM with batched + streamed responses).

``build_llm_deployment(config)`` returns a Serve Application whose
replica holds one compiled engine:

- ``__call__(prompt)`` — completion text; concurrent requests are
  merged into one device batch by @serve.batch (MXU utilization),
- ``generate_stream(prompt)`` — generator of text deltas, served over
  the handle's streaming path / HTTP chunked responses.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu import serve
from ray_tpu.llm.config import LLMConfig
from ray_tpu.models.decoding import SamplingParams


def build_llm_deployment(config: LLMConfig):
    """Build (not deploy) the Serve application for ``config``."""

    @serve.deployment(
        name=config.name,
        num_replicas=config.num_replicas,
        # concurrent handlers feed the continuous batcher's one device
        # loop — the replica must accept overlapping requests
        max_ongoing_requests=max(8, config.cache_slots * 2),
        ray_actor_options=(
            {"resources": config.resources} if config.resources else None),
    )
    class LLMServer:
        def __init__(self):
            if config.continuous_batching:
                from ray_tpu.llm.engine import ContinuousLLMEngine

                self.engine = ContinuousLLMEngine(config)
            else:
                from ray_tpu.llm.engine import LLMEngine

                self.engine = LLMEngine(config)
            self.tokenizer = self.engine.tokenizer

        @serve.batch(max_batch_size=config.batch_max_size,
                     batch_wait_timeout_s=config.batch_wait_timeout_s)
        def _generate_batch(self, prompts):
            return self.engine.generate(prompts)

        def __call__(self, prompt: str) -> str:
            if config.continuous_batching:
                from ray_tpu.serve import slo

                # iteration-level scheduling: this request joins the
                # running decode batch the moment a KV slot frees; the
                # wait is bounded by the request's deadline (expiry →
                # DeadlineExceededError → 504 at the front door)
                return slo.result_within_deadline(
                    self.engine.submit(prompt))
            return self._generate_batch(prompt)

        def engine_stats(self) -> dict:
            st = getattr(getattr(self.engine, "batcher", None), "stats",
                         None)
            return dict(st) if st is not None else {}

        def generate_stream(self, prompt: str,
                            max_tokens: Optional[int] = None):
            """Yields text deltas for one prompt (token-level streaming)."""
            sampling = self.engine.config.sampling
            if max_tokens is not None:
                sampling = dataclasses.replace(sampling,
                                               max_tokens=max_tokens)
            eos = getattr(self.tokenizer, "eos_token_id", None)
            if sampling.stop_token_id is None and eos is not None:
                sampling = dataclasses.replace(sampling, stop_token_id=eos)
            ids = self.tokenizer.encode(prompt)
            if config.continuous_batching:
                stream = self.engine.submit_stream(ids, sampling)
            else:
                stream = self.engine.generator.generate_stream(
                    ids, sampling, seed=self.engine.next_seed())
            out_ids = []
            prev_text = ""
            for t in stream:
                out_ids.append(t)
                text = self.tokenizer.decode(out_ids)
                delta, prev_text = text[len(prev_text):], text
                if delta:
                    yield delta

    return LLMServer.bind()


def serve_llm(config: LLMConfig):
    """Deploy and return the live handle (reference: ray.llm serve
    entrypoints)."""
    return serve.run(build_llm_deployment(config), name=config.name)
