"""Model zoo for the TPU framework (flagship: Llama-family decoder LM).

The reference has no native models (SURVEY.md §2.4 — Train/Serve wrap
torch/vLLM); here models are in-framework so Train/Serve/bench drive one
code path.
"""

from ray_tpu.models.transformer import (
    PRESETS,
    TransformerConfig,
    config,
    forward,
    init_params,
    loss_fn,
    param_axes,
    trainable_mask,
)

__all__ = [
    "PRESETS",
    "TransformerConfig",
    "config",
    "forward",
    "init_params",
    "loss_fn",
    "param_axes",
    "trainable_mask",
]
