"""Continuous batching: admit/evict sequences per decode step over a
fixed-slot KV cache.

Reference: the reference LLM library defers serving to vLLM
(python/ray/llm/_internal/serve/engines/vllm/) whose core idea is
iteration-level scheduling — new requests join the running batch the
moment a slot frees, instead of waiting for the whole batch to drain.
This is the TPU-native version:

- the KV cache has a FIXED number of slots (rows) and a fixed max_len —
  static shapes, so XLA compiles exactly three programs (prefill per
  length bucket, row install, one decode step) and never recompiles in
  steady state,
- one jitted decode step advances ALL active slots together (free slots
  compute too and are masked out — on TPU the batch dimension is padded
  anyway, wasted rows cost nothing vs. a recompile),
- per-slot sampling (temperature / top-k) is vectorized so requests
  with different SamplingParams share one device step,
- admission: a waiting request prefills into a standalone single-row
  cache (bucketed lengths bound compile count) and the row is scattered
  into its slot; eviction: stop-token / max_tokens / cache-full frees
  the slot the same step, and the next waiting request takes it.

``ContinuousBatcher.submit()`` is thread-safe and returns a Future; a
pump thread runs steps while any request is active or waiting — the
Serve replica's concurrent handlers all feed one device loop, keeping
the MXU busy under mixed-length traffic.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.decoding import (
    KVCache,
    SamplingParams,
    forward_cached,
    init_cache,
)
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.attention import NEG_INF


def _sample_per_slot(logits, rng, temps, topks):
    """Vectorized sampling: per-row temperature (0 = greedy) and top-k
    (0 = unfiltered). logits [B, V] -> ids [B]."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    f32 = logits.astype(jnp.float32)
    scaled = f32 / jnp.maximum(temps, 1e-6)[:, None]
    # per-row kth threshold: value at rank (top_k - 1) descending;
    # top_k == 0 disables the filter for that row
    v = logits.shape[-1]
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    idx = jnp.clip(topks - 1, 0, v - 1)[:, None]
    kth = jnp.take_along_axis(sorted_desc, idx, axis=1)
    filtered = jnp.where(
        (topks[:, None] > 0) & (scaled < kth), NEG_INF, scaled)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(
        jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


@dataclasses.dataclass
class _Request:
    tokens: List[int]
    sampling: SamplingParams
    future: Optional[Future]
    stream_q: Optional[queue.Queue]  # token stream, None-terminated
    out: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    admitted_step: int = -1


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed-slot KV cache."""

    def __init__(self, cfg: TransformerConfig, params, max_len: int = 512,
                 slots: int = 8, seed: int = 0):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self._waiting: "queue.Queue[_Request]" = queue.Queue()
        # scheduler state (_active/_free/_host_len/...) is confined to
        # the pump thread; only _waiting and stats cross threads
        self._active: Dict[int, _Request] = {}
        self._free = list(range(slots))
        self._wake = threading.Event()
        self._shutdown = False
        self._rng = jax.random.key(seed)
        self.cache = init_cache(cfg, slots, max_len)
        # per-slot host-side state (no device sync on the emit path)
        self._temps = np.zeros(slots, np.float32)
        self._topks = np.zeros(slots, np.int32)
        self._last_tok = np.zeros(slots, np.int32)
        self._host_len = np.zeros(slots, np.int64)
        # stats (observable by tests/metrics)
        self.stats = {"admitted": 0, "finished": 0, "steps": 0,
                      "max_active": 0, "tokens_out": 0,
                      "last_admit_step": -1}
        self._prefill_jits: Dict[int, Any] = {}
        self._decode_jit = jax.jit(self._decode_impl)
        self._install_jit = jax.jit(self._install_impl,
                                    donate_argnums=(0,))
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="cb-pump")
        self._thread.start()

    # -- public API -----------------------------------------------------
    def submit(self, tokens: Sequence[int],
               sampling: Optional[SamplingParams] = None) -> Future:
        """Thread-safe: enqueue one request; resolves to List[int]."""
        if self._shutdown:
            raise RuntimeError("ContinuousBatcher was shut down")
        fut: Future = Future()
        req = _Request(list(tokens) or [0], sampling or SamplingParams(),
                       fut, None)
        self._check_len(req)
        self._waiting.put(req)
        self._wake.set()
        return fut

    def submit_stream(self, tokens: Sequence[int],
                      sampling: Optional[SamplingParams] = None):
        """Yields token ids as they are emitted."""
        if self._shutdown:
            raise RuntimeError("ContinuousBatcher was shut down")
        q: queue.Queue = queue.Queue()
        req = _Request(list(tokens) or [0], sampling or SamplingParams(),
                       None, q)
        self._check_len(req)
        self._waiting.put(req)
        self._wake.set()
        while True:
            t = q.get()
            if t is None:
                return
            yield t

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        self._thread.join(timeout=10.0)
        # outstanding work can never run now: resolve it with an error
        # instead of hanging its callers
        err = RuntimeError("ContinuousBatcher was shut down")
        leftovers = list(self._active.values())
        while not self._waiting.empty():
            try:
                leftovers.append(self._waiting.get_nowait())
            except queue.Empty:
                break
        for req in leftovers:
            if req.future is not None and not req.future.done():
                req.future.set_exception(err)
            if req.stream_q is not None:
                req.stream_q.put(None)

    def _check_len(self, req: _Request) -> None:
        if len(req.tokens) >= self.max_len:
            raise ValueError(
                f"prompt length {len(req.tokens)} >= max_len "
                f"{self.max_len}")

    # -- device programs ------------------------------------------------
    def _prefill_impl(self, params, tokens, length):
        """[1, S] prompt -> (last_logits [V], row_k, row_v [L, S, kvH, D])
        against a standalone single-row cache."""
        s = tokens.shape[1]
        row_cache = init_cache(self.cfg, 1, s)
        positions = jnp.arange(s)[None, :]
        kv_mask = jnp.arange(s)[None, :] < length
        logits, row_cache = forward_cached(
            self.cfg, params, tokens, positions, row_cache, kv_mask)
        last = jnp.take_along_axis(
            logits, (length - 1)[:, None, None].repeat(
                logits.shape[-1], -1), axis=1)[:, 0]
        return last[0], row_cache.k[:, 0], row_cache.v[:, 0]

    def _install_impl(self, cache: KVCache, row_k, row_v, slot, length):
        """Scatter a prefilled row into its slot of the big cache (the
        row is padded to max_len, so the whole slot — including stale
        data from its previous occupant — is overwritten)."""
        k = jax.lax.dynamic_update_slice(
            cache.k, row_k[:, None], (0, slot, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            cache.v, row_v[:, None], (0, slot, 0, 0, 0))
        lengths = cache.lengths.at[slot].set(length)
        return KVCache(k, v, lengths)

    def _decode_impl(self, params, toks, cache, rng, temps, topks,
                     active_mask):
        positions = cache.lengths[:, None]
        kv_mask = jnp.arange(self.max_len)[None, :] <= \
            cache.lengths[:, None]
        logits, cache = forward_cached(
            self.cfg, params, toks[:, None], positions, cache, kv_mask)
        nxt = _sample_per_slot(logits[:, 0], rng, temps, topks)
        # only ACTIVE slots advance; free rows stay put so a later
        # install never races a drifting length past max_len
        new_len = jnp.where(active_mask, cache.lengths + 1, cache.lengths)
        return nxt, KVCache(cache.k, cache.v, new_len)

    # -- scheduler ------------------------------------------------------
    @staticmethod
    def _bucket(n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return b

    def _admit(self) -> bool:
        admitted = False
        while self._free and not self._waiting.empty():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            slot = self._free.pop()
            try:
                self._admit_one(req, slot)
            except Exception as e:  # noqa: BLE001 — e.g. compile OOM
                # the slot goes back and THIS request fails; others and
                # the pump survive
                self._free.append(slot)
                if req.future is not None and not req.future.done():
                    req.future.set_exception(e)
                if req.stream_q is not None:
                    req.stream_q.put(None)
                continue
            admitted = True
        self.stats["max_active"] = max(self.stats["max_active"],
                                       len(self._active))
        return admitted

    def _admit_one(self, req: _Request, slot: int) -> None:
        bucket = min(self._bucket(len(req.tokens)), self.max_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, : len(req.tokens)] = req.tokens
        pf = self._prefill_jits.get(bucket)
        if pf is None:
            pf = jax.jit(self._prefill_impl)
            self._prefill_jits[bucket] = pf
        last_logits, row_k, row_v = pf(
            self.params, jnp.asarray(toks),
            jnp.asarray([len(req.tokens)], np.int32))
        # pad the row out to max_len before install
        pad = self.max_len - row_k.shape[1]
        if pad > 0:
            zeros = jnp.zeros(
                row_k.shape[:1] + (pad,) + row_k.shape[2:],
                row_k.dtype)
            row_k = jnp.concatenate([row_k, zeros], axis=1)
            row_v = jnp.concatenate([row_v, zeros], axis=1)
        self.cache = self._install_jit(
            self.cache, row_k, row_v, slot, len(req.tokens))
        self._rng, k = jax.random.split(self._rng)
        first = _sample_per_slot(
            last_logits[None], k,
            jnp.asarray([req.sampling.temperature], np.float32),
            jnp.asarray([req.sampling.top_k], np.int32))
        req.slot = slot
        req.admitted_step = self.stats["steps"]
        self.stats["last_admit_step"] = self.stats["steps"]
        self._temps[slot] = req.sampling.temperature
        self._topks[slot] = req.sampling.top_k
        self._host_len[slot] = len(req.tokens)
        self._last_tok[slot] = int(np.asarray(first)[0])
        self._active[slot] = req
        self.stats["admitted"] += 1
        self._emit(req, self._last_tok[slot])

    def _emit(self, req: _Request, tok: int) -> None:
        """Deliver one sampled token; free the slot when the request is
        done (stop token / max_tokens / out of cache room)."""
        stop = req.sampling.stop_token_id
        done = False
        if stop is not None and tok == stop:
            done = True
        else:
            req.out.append(int(tok))
            if req.stream_q is not None:
                req.stream_q.put(int(tok))
            self.stats["tokens_out"] += 1
            if len(req.out) >= req.sampling.max_tokens:
                done = True
        # prompt_len + emitted tokens occupy the row; the NEXT decode
        # writes at position lengths[slot], which must stay < max_len —
        # matching Generator.generate's lengths >= max_len stop
        if not done and req.slot >= 0:
            if self._host_len[req.slot] >= self.max_len:
                done = True
        if done:
            self._retire(req)

    def _retire(self, req: _Request) -> None:
        if req.slot >= 0:
            self._active.pop(req.slot, None)
            self._free.append(req.slot)
            req.slot = -1
        self.stats["finished"] += 1
        if req.future is not None and not req.future.done():
            req.future.set_result(list(req.out))
        if req.stream_q is not None:
            req.stream_q.put(None)

    def _pump(self) -> None:
        while not self._shutdown:
            if not self._active and self._waiting.empty():
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            try:
                self._step()
            except Exception as e:  # noqa: BLE001 — fail active requests
                for req in list(self._active.values()):
                    if req.future is not None and not req.future.done():
                        req.future.set_exception(e)
                    if req.stream_q is not None:
                        req.stream_q.put(None)
                    self._retire_silent(req)
                import logging

                logging.getLogger(__name__).exception(
                    "continuous-batching step failed")

    def _retire_silent(self, req: _Request) -> None:
        if req.slot >= 0:
            self._active.pop(req.slot, None)
            self._free.append(req.slot)
            req.slot = -1

    def _step(self) -> None:
        self._admit()
        if not self._active:
            return
        active_mask = np.zeros(self.slots, bool)
        for slot in self._active:
            active_mask[slot] = True
        self._rng, k = jax.random.split(self._rng)
        toks, self.cache = self._decode_jit(
            self.params, jnp.asarray(self._last_tok), self.cache, k,
            jnp.asarray(self._temps), jnp.asarray(self._topks),
            jnp.asarray(active_mask))
        self.stats["steps"] += 1
        toks_np = np.asarray(toks)
        for slot, req in list(self._active.items()):
            self._host_len[slot] += 1
            self._last_tok[slot] = int(toks_np[slot])
            self._emit(req, int(toks_np[slot]))
