"""Autoregressive decoding with a KV cache for the flagship transformer.

The reference LLM library delegates generation to vLLM
(python/ray/llm/_internal/serve/engines/vllm/); here the engine is
JAX-native over ray_tpu.models.transformer — the TPU-first shape:

- prefill: ONE jitted forward over the whole (right-padded) prompt
  batch writing K/V for every layer into a preallocated cache
  [L, B, max_len, kvH, D] (static shapes — no per-token recompiles),
- decode: ONE jitted single-token step per emitted token; the layer
  stack is a `lax.scan` over (stacked params, cache layers) so the
  compiled program is independent of depth,
- sampling (greedy / temperature / top-k) happens on-device; only the
  emitted token ids cross back to host.

Left-padding-free: prompts are right-padded, per-sequence lengths track
the true positions, and attention masks cache slots >= the sequence's
current length.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import (
    TransformerConfig, _rms_norm, _rope,
)
from ray_tpu.ops.attention import NEG_INF


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, max_len, kvH, D]
    v: jax.Array  # [L, B, max_len, kvH, D]
    lengths: jax.Array  # [B] — tokens currently in cache per sequence


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               dtype=None) -> KVCache:
    dtype = dtype or cfg.dtype
    shape = (cfg.layers, batch, max_len, cfg.kv_heads, cfg.hd)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def _attend_cached(q, k_cache, v_cache, q_pos, kv_len_mask):
    """q [B,S,H,D] against the full cache [B,max_len,kvH,D].

    kv_len_mask [B, max_len] marks valid cache slots; q_pos [B,S] are the
    global positions of the queries (causal: key position <= q position).
    """
    b, s, h, d = q.shape
    kvh = k_cache.shape[2]
    rep = h // kvh
    k = jnp.repeat(k_cache, rep, axis=2) if rep > 1 else k_cache
    v = jnp.repeat(v_cache, rep, axis=2) if rep > 1 else v_cache
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / (d ** 0.5)
    t = k_cache.shape[1]
    key_pos = jnp.arange(t)[None, :]  # [1, max_len]
    causal = q_pos[:, None, :, None] >= key_pos[:, None, None, :] \
        if q_pos.ndim == 2 else None
    mask = kv_len_mask[:, None, None, :] & causal
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _block_cached(cfg: TransformerConfig, x, p, lora, positions,
                  k_cache, v_cache, kv_len_mask):
    """One decoder block against cached K/V. Returns (x, new_k, new_v)
    where new_k/new_v are this call's freshly computed K/V [B,S,kvH,D]."""
    scale = cfg.lora_alpha / cfg.lora_rank if cfg.lora_rank else 0.0
    b, s, _ = x.shape
    nh, nkv, hd = cfg.heads, cfg.kv_heads, cfg.hd

    y = _rms_norm(x, p["ln_attn"], cfg.norm_eps)
    q = jnp.einsum("bsh,hnd->bsnd", y, p["wq"].astype(y.dtype))
    k = jnp.einsum("bsh,hnd->bsnd", y, p["wk"].astype(y.dtype))
    v = jnp.einsum("bsh,hnd->bsnd", y, p["wv"].astype(y.dtype))
    if lora is not None:
        from ray_tpu.models.transformer import _lora_delta

        q = q + _lora_delta(y, lora["wq_a"], lora["wq_b"], scale).reshape(
            b, s, nh, hd)
        v = v + _lora_delta(y, lora["wv_a"], lora["wv_b"], scale).reshape(
            b, s, nkv, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    # scatter fresh K/V into the cache at each sequence's positions, then
    # attend against the whole (masked) cache
    def put(cache, new):
        bidx = jnp.arange(b)[:, None]
        return cache.at[bidx, positions].set(new.astype(cache.dtype))

    k_cache = put(k_cache, k)
    v_cache = put(v_cache, v)
    attn = _attend_cached(q, k_cache, v_cache, positions, kv_len_mask)
    attn = jnp.einsum("bsnd,ndh->bsh", attn, p["wo"].astype(attn.dtype))
    x = x + attn

    y = _rms_norm(x, p["ln_mlp"], cfg.norm_eps)
    gate = jnp.einsum("bsh,hm->bsm", y, p["wi_gate"].astype(y.dtype))
    up = jnp.einsum("bsh,hm->bsm", y, p["wi_up"].astype(y.dtype))
    if lora is not None:
        gate = gate + _lora_delta(y, lora["wi_a"], lora["wi_b"], scale)
    act = jax.nn.silu(gate) * up
    out = jnp.einsum("bsm,mh->bsh", act, p["wo_mlp"].astype(act.dtype))
    return x + out, k_cache, v_cache


def forward_cached(cfg: TransformerConfig, params, tokens, positions,
                   cache: KVCache, kv_len_mask):
    """Forward [B,S] tokens through all layers, reading+writing the cache.

    Returns (logits [B,S,V], new_cache). The layer stack is a lax.scan
    over (stacked params, cache layers) — one compiled block body.
    """
    x = params["embed"].astype(cfg.dtype)[tokens]
    blocks, lora = params["blocks"], params.get("lora")
    layer_tree = {"p": blocks}
    if lora is not None:
        layer_tree["l"] = lora

    def body(x, layer):
        out, kc, vc = _block_cached(
            cfg, x, layer["p"], layer.get("l"), positions,
            layer["k"], layer["v"], kv_len_mask)
        return out, (kc, vc)

    x, (new_k, new_v) = lax.scan(
        body, x, dict(layer_tree, k=cache.k, v=cache.v))
    x = _rms_norm(x, params["ln_f"], cfg.norm_eps)
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsh,hv->bsv", x, unembed.astype(x.dtype))
    return logits, KVCache(new_k, new_v, cache.lengths)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Reference surface: vLLM SamplingParams (the subset that matters)."""

    max_tokens: int = 64
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0  # 0 = no top-k filter
    stop_token_id: Optional[int] = None


def _sample(logits, rng, temperature: float, top_k: int):
    """logits [B,V] → token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class Generator:
    """Compiled prefill + decode loop over one parameter set.

    Built once per (batch, max_len) shape bucket; generate() runs
    prompts → completions without recompiling.
    """

    def __init__(self, cfg: TransformerConfig, params, max_len: int = 512):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self._prefill = jax.jit(self._prefill_impl)
        self._decode = jax.jit(
            self._decode_impl, static_argnames=("temperature", "top_k"))

    def _prefill_impl(self, params, tokens, lengths, cache):
        b, s = tokens.shape
        positions = jnp.arange(s)[None, :].repeat(b, 0)
        kv_mask = jnp.arange(self.max_len)[None, :] < lengths[:, None]
        logits, cache = forward_cached(
            self.cfg, params, tokens, positions, cache, kv_mask)
        # logits at each prompt's LAST real token
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None].repeat(
                logits.shape[-1], -1), axis=1)[:, 0]
        return last, KVCache(cache.k, cache.v, lengths)

    def _decode_impl(self, params, tok, cache, rng, *, temperature, top_k):
        b = tok.shape[0]
        positions = cache.lengths[:, None]  # next slot per sequence
        kv_mask = jnp.arange(self.max_len)[None, :] <= cache.lengths[:, None]
        logits, cache = forward_cached(
            self.cfg, params, tok[:, None], positions, cache, kv_mask)
        nxt = _sample(logits[:, 0], rng, temperature, top_k)
        return nxt, KVCache(cache.k, cache.v, cache.lengths + 1)

    def generate(self, prompts, sampling: Optional[SamplingParams] = None,
                 seed: int = 0):
        """prompts: list of int32 token-id lists → list of completions
        (token-id lists, stop token excluded)."""
        import numpy as np

        sampling = sampling or SamplingParams()
        b = len(prompts)
        lens = np.array([len(p) for p in prompts], np.int32)
        if int(lens.max()) >= self.max_len:
            # JAX silently drops out-of-bounds cache scatters — without
            # this check an over-long prompt would "generate" garbage
            raise ValueError(
                f"prompt length {int(lens.max())} >= max_len "
                f"{self.max_len}; raise Generator(max_len=...)")
        s = int(lens.max())
        toks = np.zeros((b, s), np.int32)
        for i, p in enumerate(prompts):
            toks[i, : len(p)] = p
        cache = init_cache(self.cfg, b, self.max_len)
        last_logits, cache = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray(lens), cache)
        rng = jax.random.key(seed)
        rng, k0 = jax.random.split(rng)
        tok = _sample(last_logits, k0, sampling.temperature, sampling.top_k)
        outs = [[] for _ in range(b)]
        done = np.zeros(b, bool)
        for _ in range(sampling.max_tokens):
            tok_np = np.asarray(tok)
            for i in range(b):
                if not done[i]:
                    if sampling.stop_token_id is not None and \
                            int(tok_np[i]) == sampling.stop_token_id:
                        done[i] = True
                    else:
                        outs[i].append(int(tok_np[i]))
            # a sequence whose next KV slot is out of room stops alone —
            # cache rows are per-sequence, so others keep decoding
            lens_np = np.asarray(cache.lengths)
            for i in range(b):
                if not done[i] and lens_np[i] >= self.max_len:
                    done[i] = True
            if done.all():
                break
            rng, k = jax.random.split(rng)
            tok, cache = self._decode(
                self.params, tok, cache, k,
                temperature=sampling.temperature, top_k=sampling.top_k)
        return outs

    def generate_stream(self, prompt, sampling: Optional[SamplingParams] = None,
                        seed: int = 0):
        """Single-prompt streaming: yields one token id at a time (the
        Serve LLM deployment's token-stream path)."""
        import numpy as np

        sampling = sampling or SamplingParams()
        prompt = list(prompt) or [0]
        if len(prompt) >= self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len {self.max_len}; "
                f"raise Generator(max_len=...)")
        toks = np.asarray([prompt], np.int32)
        lens = np.asarray([len(prompt)], np.int32)
        cache = init_cache(self.cfg, 1, self.max_len)
        last_logits, cache = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray(lens), cache)
        rng = jax.random.key(seed)
        rng, k0 = jax.random.split(rng)
        tok = _sample(last_logits, k0, sampling.temperature, sampling.top_k)
        for _ in range(sampling.max_tokens):
            t = int(np.asarray(tok)[0])
            if sampling.stop_token_id is not None and \
                    t == sampling.stop_token_id:
                return
            yield t
            if int(np.asarray(cache.lengths)[0]) >= self.max_len:
                return
            rng, k = jax.random.split(rng)
            tok, cache = self._decode(
                self.params, tok, cache, k,
                temperature=sampling.temperature, top_k=sampling.top_k)
