"""Disaggregated prefill: a prefill replica computes prompt KV, a
decode replica consumes it — KV crosses processes through a typed
tensor channel, never the pickle path.

Reference: python/ray/llm/_internal/serve/engines/vllm/kv_transfer/ —
the reference splits prefill and decode across engine replicas and
ships KV blocks through a connector (NIXL / shared memory). The TPU
rebuild: the prefill replica runs ONE bucketed prefill program per
prompt-length bucket, writes the resulting [L, max_len, kvH, D] row
into a fixed-shape ``TensorChannel`` (shared-memory, zero pickle), and
the decode replica installs it straight into its paged pool
(``PagedBatcher.submit_prefilled``) and continuous-batches decode.

Why it matters on TPU: prefill is compute-bound (MXU saturating) while
decode is memory-bound (HBM streaming); separate replicas mean each
can be provisioned and batched on its own terms — the reference's
motivation, unchanged by the hardware.

Pairing protocol: one caller submits ``prefill.remote`` then
``decode.remote`` for each request; actor task ordering per caller
plus the channel's one-slot ack backpressure keep the KV rows and
decode admissions in lockstep — no sequence numbers needed. The
channel is same-host shared memory; cross-host disaggregation rides
the object-store path instead (``RowHandle`` falls back to plasma).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.experimental.channel import TensorChannel
from ray_tpu.models.decoding import SamplingParams
from ray_tpu.models.transformer import TransformerConfig

_TRANSPORT_DTYPE = "float32"  # numpy has no bfloat16; rows are cast


def _row_shape(cfg: TransformerConfig, max_len: int):
    # [2 (k/v), L, max_len, kvH, D]
    return (2, cfg.layers, max_len, cfg.kv_heads, cfg.hd)


@ray_tpu.remote(max_concurrency=1)
class PrefillReplica:
    """Computes prompt KV rows and streams them into the channel.

    max_concurrency=1: a single-threaded actor executes its tasks in
    enqueue order, so KV rows enter the channel in the same order the
    engine assigned ticket numbers — the decode side's ticket gate
    (DecodeReplica.generate) then pairs rows to requests exactly."""

    def __init__(self, cfg: TransformerConfig, params, max_len: int,
                 channel: TensorChannel):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.decoding import forward_cached, init_cache

        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.channel = channel
        self._jits: Dict[int, Any] = {}

        def _prefill(params, tokens, length):
            s = tokens.shape[1]
            row = init_cache(cfg, 1, s)
            positions = jnp.arange(s)[None, :]
            kv_mask = jnp.arange(s)[None, :] < length
            logits, row = forward_cached(cfg, params, tokens, positions,
                                         row, kv_mask)
            last = jnp.take_along_axis(
                logits, (length - 1)[:, None, None].repeat(
                    logits.shape[-1], -1), axis=1)[:, 0]
            return last[0], row.k[:, 0], row.v[:, 0]

        self._impl = _prefill
        self._jax = jax

    def prefill(self, tokens: Sequence[int]):
        """Returns (n_tokens, last_logits) on the object path; the KV
        row goes out-of-band through the tensor channel."""
        import jax

        n = len(tokens)
        bucket = 16
        while bucket < n:
            bucket *= 2
        bucket = min(bucket, self.max_len)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = tokens
        fn = self._jits.get(bucket)
        if fn is None:
            fn = jax.jit(self._impl)
            self._jits[bucket] = fn
        last, row_k, row_v = fn(self.params, toks,
                                np.asarray([n], np.int32))
        row = np.zeros(_row_shape(self.cfg, self.max_len),
                       _TRANSPORT_DTYPE)
        row[0, :, :bucket] = np.asarray(row_k, np.float32)
        row[1, :, :bucket] = np.asarray(row_v, np.float32)
        self.channel.write(row, timeout=120.0)
        return n, np.asarray(last, np.float32)


@ray_tpu.remote(max_concurrency=4)
class DecodeReplica:
    """Owns the paged pool; admits prefilled rows and decodes."""

    def __init__(self, cfg: TransformerConfig, params, max_len: int,
                 slots: int, page_size: int, reader):
        import threading

        from ray_tpu.models.paged_kv import PagedBatcher

        self.batcher = PagedBatcher(cfg, params, max_len=max_len,
                                    slots=slots, page_size=page_size)
        self.reader = reader
        # ticket gate: generate() may run on several actor threads, but
        # channel reads MUST happen in the engine's ticket order or two
        # same-length prompts could swap KV rows undetectably
        self._next_ticket = 0
        self._ticket_cv = threading.Condition()

    def generate(self, tokens: Sequence[int], prefill_meta,
                 sampling: Optional[SamplingParams] = None,
                 ticket: int = 0) -> List[int]:
        """prefill_meta is PrefillReplica.prefill's return (resolved by
        the runtime when the prefill task finishes — by which time its
        KV row is already in, or entering, the channel)."""
        n, last_logits = prefill_meta
        assert n == len(tokens), "prefill/decode pairing broke"
        with self._ticket_cv:
            while ticket != self._next_ticket:
                if not self._ticket_cv.wait(timeout=300.0):
                    raise TimeoutError(
                        f"ticket {ticket} starved (next="
                        f"{self._next_ticket})")
            row = self.reader.read(timeout=120.0)
            self._next_ticket += 1
            self._ticket_cv.notify_all()
        fut = self.batcher.submit_prefilled(
            tokens, row[0], row[1], last_logits, sampling)
        return fut.result(timeout=300.0)

    def stats(self) -> Dict[str, int]:
        return dict(self.batcher.stats)

    def close(self) -> bool:
        self.batcher.shutdown()
        return True


class DisaggPrefillEngine:
    """Two-replica engine: ``generate`` fans a request through the
    prefill replica into the decode replica and returns the sampled
    tokens. Construction is driver-side; both replicas live on the
    local node (the KV channel is shared memory)."""

    def __init__(self, cfg: TransformerConfig, params, max_len: int = 256,
                 slots: int = 4, page_size: int = 32,
                 num_cpus: float = 0.5):
        self.channel = TensorChannel(_row_shape(cfg, max_len),
                                     _TRANSPORT_DTYPE)
        self.prefiller = PrefillReplica.options(num_cpus=num_cpus).remote(
            cfg, params, max_len, self.channel)
        self.decoder = DecodeReplica.options(num_cpus=num_cpus).remote(
            cfg, params, max_len, slots, page_size, self.channel.reader(0))
        self._ticket = 0

    def generate(self, tokens: Sequence[int],
                 sampling: Optional[SamplingParams] = None):
        """Returns a ref resolving to the sampled token list."""
        ticket = self._ticket
        self._ticket += 1
        meta = self.prefiller.prefill.remote(list(tokens))
        return self.decoder.generate.remote(list(tokens), meta, sampling,
                                            ticket=ticket)

    def stats(self) -> Dict[str, int]:
        return ray_tpu.get(self.decoder.stats.remote())

    def shutdown(self) -> None:
        try:
            ray_tpu.get(self.decoder.close.remote(), timeout=30)
        except Exception:  # noqa: BLE001
            pass
        for a in (self.prefiller, self.decoder):
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        try:
            self.channel.close()
        except Exception:  # noqa: BLE001
            pass
