"""Block-paged KV cache with prefix reuse — the TPU-native answer to
vLLM's PagedAttention + automatic prefix caching.

Reference: the reference LLM library delegates KV management to vLLM
(python/ray/llm/_internal/serve/engines/vllm/), whose memory model is
fixed-size KV pages + a per-sequence page table + copy-on-write prefix
sharing. This module rebuilds that model under XLA's constraints:

- **One physical pool** ``[L, num_pages, page_size, kvH, D]`` for K and
  V. Page tables are ``[slots, pages_per_seq]`` int32 — every shape is
  static, so steady state runs exactly three compiled programs (prefill
  per length bucket, page install, one decode step) and never
  recompiles.
- **Decode** gathers each active slot's pages into a contiguous view
  *inside* the per-layer scan body (``pool[l][page_table]``) — the
  transient is one layer's worth, not a dense cache — attends, then
  scatters the new K/V into the slot's current write page. Inactive
  slots write to a reserved trash page (page 0), so the step needs no
  host-side branching.
- **Prefix reuse**: pages are refcounted; a finished sequence's prompt
  pages register content hashes at full-page granularity. A new prompt
  reuses the longest cached chain of FULL pages (incref — shared pages
  are never written: decode only appends to a sequence's private last
  page) and prefills just the remainder, attending over the reused
  prefix gathered into the prefill row. Freed pages stay cached (rc=0,
  on the LRU free list) until the allocator reclaims them, exactly
  vLLM's "cached-free" state.
- **Disaggregated prefill**: ``submit_prefilled`` admits a request
  whose KV row was computed elsewhere (a prefill replica shipping over
  a typed tensor channel — see models/disagg_prefill.py), installing
  pages without running local prefill.
"""

from __future__ import annotations

import dataclasses
import hashlib
import queue
import threading
from collections import OrderedDict
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.models.continuous_batching import _sample_per_slot
from ray_tpu.models.decoding import (
    SamplingParams,
    _block_cached,
    _rms_norm,
    forward_cached,
    init_cache,
)
from ray_tpu.models.transformer import TransformerConfig


class KVPoolExhausted(RuntimeError):
    """No free pages. A RuntimeError subclass so existing callers that
    catch the old bare RuntimeError keep working; the batcher's admit
    path catches THIS to requeue instead of failing the request."""


class PagedKV:
    """Host-side page bookkeeping: refcounts, free list, prefix map."""

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.rc = np.zeros(num_pages, np.int32)
        self.rc[0] = 1  # page 0 = trash page, never allocated
        # free pages in LRU order; a freed page keeps its content (and
        # its prefix-map entry) until reallocated
        self.free: "OrderedDict[int, None]" = OrderedDict(
            (i, None) for i in range(1, num_pages))
        # prefix hash -> page id holding that page of the prefix
        self.prefix_map: Dict[str, int] = {}
        self.page_key: Dict[int, str] = {}  # inverse, for invalidation
        self.stats = {"prefix_hit_pages": 0, "alloc_pages": 0,
                      "evicted_entries": 0}

    def alloc(self) -> int:
        """Pop the least-recently-freed page, invalidating whatever
        prefix entry still pointed at its old content."""
        if not self.free:
            raise KVPoolExhausted("KV pool exhausted")
        page, _ = self.free.popitem(last=False)
        old_key = self.page_key.pop(page, None)
        if old_key is not None and self.prefix_map.get(old_key) == page:
            del self.prefix_map[old_key]
            self.stats["evicted_entries"] += 1
        self.rc[page] = 1
        self.stats["alloc_pages"] += 1
        return page

    def incref(self, page: int) -> None:
        if self.rc[page] == 0:
            self.free.pop(page, None)  # cached-free -> live again
        self.rc[page] += 1

    def decref(self, page: int) -> None:
        self.rc[page] -= 1
        if self.rc[page] == 0:
            self.free[page] = None  # to the LRU tail, content retained

    def lookup_prefix(self, keys: List[str]) -> List[int]:
        """Longest chain of cached pages matching the prefix keys."""
        pages: List[int] = []
        for key in keys:
            page = self.prefix_map.get(key)
            if page is None:
                break
            pages.append(page)
        self.stats["prefix_hit_pages"] += len(pages)
        return pages

    def register_prefix(self, keys: List[str], pages: List[int]) -> None:
        for key, page in zip(keys, pages):
            if key not in self.prefix_map:
                self.prefix_map[key] = page
                self.page_key[page] = key


def prefix_keys(tokens: Sequence[int], page_size: int) -> List[str]:
    """One content hash per FULL page of the prompt: key i covers
    tokens[:page_size*(i+1)] — a chain, so matching key i implies the
    whole prefix up to that page matches."""
    keys = []
    h = hashlib.sha1()
    full_pages = len(tokens) // page_size
    for i in range(full_pages):
        chunk = tokens[i * page_size:(i + 1) * page_size]
        h.update(np.asarray(chunk, np.int32).tobytes())
        keys.append(h.hexdigest())
    return keys


@dataclasses.dataclass
class _Request:
    tokens: List[int]
    sampling: SamplingParams
    future: Optional[Future]
    stream_q: Optional[queue.Queue]
    # disaggregated prefill: KV row + last logits computed elsewhere
    premade_row: Optional[Tuple[Any, Any, Any]] = None
    out: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    pages: List[int] = dataclasses.field(default_factory=list)


class PagedBatcher:
    """Continuous batching over the paged pool. API mirrors
    models/continuous_batching.ContinuousBatcher (submit/submit_stream/
    shutdown + stats) so engines can swap slot-dense for paged."""

    def __init__(self, cfg: TransformerConfig, params, max_len: int = 512,
                 slots: int = 8, page_size: int = 64,
                 extra_pages: int = 0, seed: int = 0,
                 num_pages: Optional[int] = None):
        """``num_pages`` overrides the pool size: smaller than
        1 + slots*pages_per_seq overcommits memory (lazy growth +
        recompute-preemption absorb the shortfall — vLLM's model);
        ``extra_pages`` adds headroom so freed prefix pages survive
        longer in the cache."""
        if max_len % page_size != 0:
            raise ValueError("max_len must be a multiple of page_size")
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.page_size = page_size
        self.pages_per_seq = max_len // page_size
        self.slots = slots
        if num_pages is None:
            num_pages = 1 + slots * self.pages_per_seq + extra_pages
        self.kv = PagedKV(num_pages, page_size)
        shape = (cfg.layers, num_pages, page_size, cfg.kv_heads, cfg.hd)
        self.pool_k = jnp.zeros(shape, cfg.dtype)
        self.pool_v = jnp.zeros(shape, cfg.dtype)
        # per-slot host state
        self._page_table = np.zeros((slots, self.pages_per_seq), np.int32)
        self._lengths = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)
        self._topks = np.zeros(slots, np.int32)
        self._last_tok = np.zeros(slots, np.int32)
        self._active: Dict[int, _Request] = {}
        self._free_slots = list(range(slots))
        self._waiting: "queue.Queue[_Request]" = queue.Queue()
        self._wake = threading.Event()
        self._shutdown = False
        self._rng = jax.random.key(seed)
        self.stats = {"admitted": 0, "finished": 0, "steps": 0,
                      "tokens_out": 0, "prefill_tokens": 0,
                      "prefix_hit_tokens": 0, "preempted": 0}
        self._decode_jit = jax.jit(self._decode_impl,
                                   donate_argnums=(2, 3))
        self._install_jit = jax.jit(self._install_impl,
                                    donate_argnums=(0, 1))
        self._prefill_jits: Dict[int, Any] = {}
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="paged-pump")
        self._thread.start()

    # -- public API -----------------------------------------------------
    def submit(self, tokens: Sequence[int],
               sampling: Optional[SamplingParams] = None) -> Future:
        return self._enqueue(tokens, sampling, stream=False)

    def submit_stream(self, tokens: Sequence[int],
                      sampling: Optional[SamplingParams] = None):
        req = self._enqueue(tokens, sampling, stream=True)
        while True:
            t = req.get()
            if t is None:
                return
            yield t

    def submit_prefilled(self, tokens: Sequence[int], row_k, row_v,
                         last_logits,
                         sampling: Optional[SamplingParams] = None
                         ) -> Future:
        """Admit a request whose prompt KV was computed by a prefill
        replica (disaggregated prefill — reference:
        llm/_internal/serve/engines/vllm/kv_transfer/). ``row_k/row_v``
        are [L, S, kvH, D] with S >= len(tokens)."""
        if self._shutdown:
            raise RuntimeError("PagedBatcher was shut down")
        fut: Future = Future()
        req = _Request(list(tokens) or [0], sampling or SamplingParams(),
                       fut, None,
                       premade_row=(jnp.asarray(row_k), jnp.asarray(row_v),
                                    jnp.asarray(last_logits)))
        self._check_len(req)
        self._waiting.put(req)
        self._wake.set()
        return fut

    def _enqueue(self, tokens, sampling, stream: bool):
        if self._shutdown:
            raise RuntimeError("PagedBatcher was shut down")
        q: Optional[queue.Queue] = queue.Queue() if stream else None
        fut: Optional[Future] = None if stream else Future()
        req = _Request(list(tokens) or [0], sampling or SamplingParams(),
                       fut, q)
        self._check_len(req)
        self._waiting.put(req)
        self._wake.set()
        return q if stream else fut

    def _check_len(self, req: _Request) -> None:
        if len(req.tokens) >= self.max_len:
            raise ValueError(
                f"prompt length {len(req.tokens)} >= max_len "
                f"{self.max_len}")

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake.set()
        self._thread.join(timeout=10.0)
        err = RuntimeError("PagedBatcher was shut down")
        leftovers = list(self._active.values())
        while not self._waiting.empty():
            try:
                leftovers.append(self._waiting.get_nowait())
            except queue.Empty:
                break
        for req in leftovers:
            if req.future is not None and not req.future.done():
                req.future.set_exception(err)
            if req.stream_q is not None:
                req.stream_q.put(None)

    # -- device programs ------------------------------------------------
    def _prefill_impl(self, params, tokens, length, prefix_row_k,
                      prefix_row_v, prefix_len):
        """Continuation prefill: [1, S] remainder tokens at positions
        prefix_len.., attending over the reused prefix (gathered into
        the row) plus themselves. Returns (last_logits [V], row_k,
        row_v [L, max_len, kvH, D])."""
        s = tokens.shape[1]
        row = init_cache(self.cfg, 1, self.max_len)
        k = lax.dynamic_update_slice(
            row.k, prefix_row_k[:, None], (0, 0, 0, 0, 0))
        v = lax.dynamic_update_slice(
            row.v, prefix_row_v[:, None], (0, 0, 0, 0, 0))
        row = row._replace(k=k, v=v)
        positions = prefix_len + jnp.arange(s)[None, :]
        kv_mask = jnp.arange(self.max_len)[None, :] < (prefix_len + s)
        logits, row = forward_cached(
            self.cfg, params, tokens, positions, row, kv_mask)
        last = jnp.take_along_axis(
            logits, (length - prefix_len - 1)[:, None, None].repeat(
                logits.shape[-1], -1), axis=1)[:, 0]
        return last[0], row.k[:, 0], row.v[:, 0]

    def _install_impl(self, pool_k, pool_v, row_k, row_v, page_ids):
        """Scatter a [L, max_len] row into the pool at page_ids
        [pages_per_seq] (trash page 0 for pages not to keep)."""
        ps = self.page_size
        lk = row_k.reshape(row_k.shape[0], self.pages_per_seq, ps,
                           *row_k.shape[2:])
        lv = row_v.reshape(row_v.shape[0], self.pages_per_seq, ps,
                           *row_v.shape[2:])
        return (pool_k.at[:, page_ids].set(lk.astype(pool_k.dtype)),
                pool_v.at[:, page_ids].set(lv.astype(pool_v.dtype)))

    def _gather_row_impl(self, pool_k, pool_v, page_ids):
        """[pages_per_seq] page ids -> dense [L, max_len] row (for
        continuation prefill over a reused prefix)."""
        k = pool_k[:, page_ids]  # [L, P, ps, kvH, D]
        v = pool_v[:, page_ids]
        ln = k.shape[0]
        return (k.reshape(ln, self.max_len, *k.shape[3:]),
                v.reshape(ln, self.max_len, *v.shape[3:]))

    def _decode_impl(self, params, toks, pool_k, pool_v, page_table,
                     lengths, rng, temps, topks, active_mask):
        """One decode step for all slots over the paged pool."""
        cfg = self.cfg
        b = toks.shape[0]
        ps = self.page_size
        positions = lengths[:, None]  # [B, 1]
        t_total = self.pages_per_seq * ps
        kv_mask = jnp.arange(t_total)[None, :] <= lengths[:, None]
        # current write target per slot; inactive slots hit trash page 0
        cur_page = jnp.where(
            active_mask,
            page_table[jnp.arange(b), lengths // ps], 0)
        cur_off = jnp.where(active_mask, lengths % ps, 0)

        x = params["embed"].astype(cfg.dtype)[toks[:, None]]
        blocks, lora = params["blocks"], params.get("lora")
        layer_tree = {"p": blocks}
        if lora is not None:
            layer_tree["l"] = lora

        def body(x, layer):
            # dense per-layer view of each slot's pages (transient —
            # one layer only, the pool itself stays paged)
            kd = layer["k"][page_table].reshape(
                b, t_total, cfg.kv_heads, cfg.hd)
            vd = layer["v"][page_table].reshape(
                b, t_total, cfg.kv_heads, cfg.hd)
            out, new_k_layer, new_v_layer = _block_cached(
                cfg, x, layer["p"], layer.get("l"), positions,
                kd, vd, kv_mask)
            # fresh K/V of the current token sits at position `lengths`
            # of the dense view — pull it out and persist into the pool
            fresh_k = new_k_layer[jnp.arange(b), lengths]  # [B, kvH, D]
            fresh_v = new_v_layer[jnp.arange(b), lengths]
            pk = layer["k"].at[cur_page, cur_off].set(
                fresh_k.astype(layer["k"].dtype))
            pv = layer["v"].at[cur_page, cur_off].set(
                fresh_v.astype(layer["v"].dtype))
            return out, (pk, pv)

        x, (new_pool_k, new_pool_v) = lax.scan(
            body, x, dict(layer_tree, k=pool_k, v=pool_v))
        x = _rms_norm(x, params["ln_f"], cfg.norm_eps)
        unembed = params.get("unembed")
        if unembed is None:
            unembed = params["embed"].T
        logits = jnp.einsum("bsh,hv->bsv", x, unembed.astype(x.dtype))
        nxt = _sample_per_slot(logits[:, 0], rng, temps, topks)
        new_len = jnp.where(active_mask, lengths + 1, lengths)
        return nxt, new_pool_k, new_pool_v, new_len

    # -- scheduler ------------------------------------------------------
    @staticmethod
    def _bucket(n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return b

    def _admit(self) -> None:
        while self._free_slots and not self._waiting.empty():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                break
            slot = self._free_slots.pop()
            try:
                self._admit_one(req, slot)
            except Exception as e:  # noqa: BLE001
                self._free_slots.append(slot)
                # _admit_one grows req.pages INCREMENTALLY (reused-prefix
                # increfs first, then each fresh alloc as it happens), so
                # this decref sweep releases everything a partial admit
                # acquired — no page leaks on pool exhaustion mid-admit
                for page in req.pages:
                    self.kv.decref(page)
                req.pages = []
                never_fits = (len(req.tokens) // self.page_size + 1
                              > self.kv.num_pages - 1)  # page 0 = trash
                if isinstance(e, KVPoolExhausted) and not never_fits:
                    # transient: active sequences hold the pool. Requeue
                    # at the FRONT (FIFO position kept — a tail requeue
                    # would let every later small request leapfrog a big
                    # one forever, its future never resolving) and stop
                    # admitting; retired sequences free pages and the
                    # pump re-runs _admit every step. (A request bigger
                    # than the whole pool still fails: requeueing it
                    # would spin forever.)
                    with self._waiting.mutex:
                        self._waiting.queue.appendleft(req)
                        self._waiting.not_empty.notify()
                    break
                if req.future is not None and not req.future.done():
                    req.future.set_exception(e)
                if req.stream_q is not None:
                    req.stream_q.put(None)

    def _padded_page_ids(self, pages: List[int]) -> np.ndarray:
        ids = np.zeros(self.pages_per_seq, np.int32)
        ids[:len(pages)] = pages
        return ids

    def _admit_one(self, req: _Request, slot: int) -> None:
        n = len(req.tokens)
        keys = prefix_keys(req.tokens, self.page_size)
        if req.premade_row is not None:
            reused: List[int] = []  # KV arrived whole from the prefiller
        else:
            reused = self.kv.lookup_prefix(keys)
            # reuse must leave at least one token to prefill (the last
            # logits come from the prefill forward)
            while reused and len(reused) * self.page_size >= n:
                self.kv.stats["prefix_hit_pages"] -= 1
                reused.pop()
        # every acquisition lands in req.pages IMMEDIATELY so the
        # _admit cleanup path can decref exactly what was taken when an
        # alloc below raises mid-admit (incref'd reused-prefix pages and
        # partial fresh allocations both leaked before)
        req.pages = []
        for page in reused:
            self.kv.incref(page)
            req.pages.append(page)
        prefix_len = len(reused) * self.page_size
        self.stats["prefix_hit_tokens"] += prefix_len
        # LAZY allocation: only the pages the sequence occupies right
        # now (prompt + the first decode write at position n) — growth
        # happens per step in _grow_pages; this is what lets the pool be
        # smaller than slots × pages_per_seq (vLLM's overcommit)
        n_pages_now = n // self.page_size + 1
        for _ in range(n_pages_now - len(reused)):
            req.pages.append(self.kv.alloc())
        page_ids = self._padded_page_ids(req.pages)

        if req.premade_row is not None:
            row_k, row_v, last_logits = req.premade_row
            pad = self.max_len - row_k.shape[1]
            if pad > 0:
                z = jnp.zeros(row_k.shape[:1] + (pad,) + row_k.shape[2:],
                              row_k.dtype)
                row_k = jnp.concatenate([row_k, z], axis=1)
                row_v = jnp.concatenate([row_v, z], axis=1)
            self.pool_k, self.pool_v = self._install_jit(
                self.pool_k, self.pool_v, row_k, row_v,
                jnp.asarray(page_ids))
        else:
            remainder = req.tokens[prefix_len:]
            bucket = min(self._bucket(len(remainder)),
                         self.max_len - prefix_len)
            bucket = max(bucket, len(remainder))
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :len(remainder)] = remainder
            prefix_k, prefix_v = self._gather_row_impl(
                self.pool_k, self.pool_v, jnp.asarray(page_ids))
            pf = self._prefill_jits.get(bucket)
            if pf is None:
                pf = jax.jit(self._prefill_impl)
                self._prefill_jits[bucket] = pf
            last_logits, row_k, row_v = pf(
                self.params, jnp.asarray(toks),
                jnp.asarray([n], np.int32), prefix_k, prefix_v,
                jnp.asarray(prefix_len, np.int32))
            self.stats["prefill_tokens"] += len(remainder)
            self.pool_k, self.pool_v = self._install_jit(
                self.pool_k, self.pool_v, row_k, row_v,
                jnp.asarray(page_ids))

        self._rng, k = jax.random.split(self._rng)
        first = _sample_per_slot(
            last_logits[None], k,
            jnp.asarray([req.sampling.temperature], np.float32),
            jnp.asarray([req.sampling.top_k], np.int32))
        req.slot = slot
        self._page_table[slot] = page_ids
        self._lengths[slot] = n
        self._temps[slot] = req.sampling.temperature
        self._topks[slot] = req.sampling.top_k
        self._last_tok[slot] = int(np.asarray(first)[0])
        self._active[slot] = req
        self.stats["admitted"] += 1
        self._emit(req, self._last_tok[slot])

    def _emit(self, req: _Request, tok: int) -> None:
        stop = req.sampling.stop_token_id
        done = False
        if stop is not None and tok == stop:
            done = True
        else:
            req.out.append(int(tok))
            if req.stream_q is not None:
                req.stream_q.put(int(tok))
            self.stats["tokens_out"] += 1
            if len(req.out) >= req.sampling.max_tokens:
                done = True
        if not done and req.slot >= 0 and \
                self._lengths[req.slot] >= self.max_len - 1:
            done = True
        if done:
            self._retire(req)

    def _retire(self, req: _Request) -> None:
        if req.slot >= 0:
            # register this prompt's full pages for future prefix hits
            keys = prefix_keys(req.tokens, self.page_size)
            self.kv.register_prefix(keys, req.pages[:len(keys)])
            for page in req.pages:
                self.kv.decref(page)
            req.pages = []
            self._active.pop(req.slot, None)
            self._free_slots.append(req.slot)
            req.slot = -1
        self.stats["finished"] += 1
        if req.future is not None and not req.future.done():
            req.future.set_result(list(req.out))
        if req.stream_q is not None:
            req.stream_q.put(None)

    def _pump(self) -> None:
        while not self._shutdown:
            if not self._active and self._waiting.empty():
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            try:
                self._step()
            except Exception as e:  # noqa: BLE001
                for req in list(self._active.values()):
                    if req.future is not None and not req.future.done():
                        req.future.set_exception(e)
                    if req.stream_q is not None:
                        req.stream_q.put(None)
                    if req.slot >= 0:
                        for page in req.pages:
                            self.kv.decref(page)
                        req.pages = []
                        self._active.pop(req.slot, None)
                        self._free_slots.append(req.slot)
                        req.slot = -1
                import logging

                logging.getLogger(__name__).exception(
                    "paged decode step failed")

    def _grow_pages(self) -> None:
        """Per-step lazy growth: every active slot must own the page its
        next decode write lands in. Pool exhausted → preempt the most
        recently admitted slot (free its pages, requeue it — it
        re-prefills from prompt+generated when room returns), matching
        vLLM's recompute-preemption policy."""
        for slot in sorted(self._active):
            req = self._active[slot]
            need = int(self._lengths[slot]) // self.page_size
            while need >= len(req.pages):
                try:
                    page = self.kv.alloc()
                except RuntimeError:
                    # prefer preempting a DIFFERENT slot; if this is the
                    # only active one it preempts itself and returns
                    candidates = [s for s in self._active if s != slot]
                    victim = candidates[-1] if candidates else slot
                    self._preempt(victim)
                    if victim == slot:
                        return
                    continue
                req.pages.append(page)
                self._page_table[slot, len(req.pages) - 1] = page

    def _preempt(self, slot: int) -> None:
        req = self._active.pop(slot)
        for page in req.pages:
            self.kv.decref(page)
        req.pages = []
        # recompute-preemption: when a slot frees up the request
        # re-prefills over prompt + everything generated so far and
        # resumes sampling from there. Already-emitted tokens stay
        # emitted (req.out keeps the max_tokens accounting).
        req.tokens = list(req.tokens) + list(req.out)
        req.premade_row = None  # its KV is gone; must re-prefill
        req.slot = -1
        self._free_slots.append(slot)
        self.stats["preempted"] += 1
        self._waiting.put(req)

    def _step(self) -> None:
        self._admit()
        if not self._active:
            return
        self._grow_pages()
        if not self._active:
            return
        active_mask = np.zeros(self.slots, bool)
        for slot in self._active:
            active_mask[slot] = True
        self._rng, k = jax.random.split(self._rng)
        toks, self.pool_k, self.pool_v, new_len = self._decode_jit(
            self.params, jnp.asarray(self._last_tok), self.pool_k,
            self.pool_v, jnp.asarray(self._page_table),
            jnp.asarray(self._lengths), k, jnp.asarray(self._temps),
            jnp.asarray(self._topks), jnp.asarray(active_mask))
        self.stats["steps"] += 1
        # np.array (copy): asarray of a jax Array is a read-only view,
        # and _admit_one writes per-slot lengths in place
        self._lengths = np.array(new_len)
        toks_np = np.asarray(toks)
        for slot, req in list(self._active.items()):
            self._last_tok[slot] = int(toks_np[slot])
            self._emit(req, int(toks_np[slot]))

    def decode_cache_size(self) -> int:
        """Compiled-program count for the decode step (steady-state
        no-recompile assertion hook)."""
        return int(self._decode_jit._cache_size())
