"""Flagship model family: Llama-style decoder-only transformer, TPU-first.

Pure-functional JAX (no module framework): a model is (config, params
pytree, apply fn). Every parameter leaf has a matching *logical axis*
tuple (see ``param_axes``) that ray_tpu.parallel.sharding maps onto the
device mesh — so DP/FSDP/TP/SP are all just rule-table choices over one
program (SURVEY.md §2.3 "parallelism strategies").

The reference framework has no native models (it defers to torch/vLLM;
SURVEY.md §2.4) — here the flagship model lives inside the framework
because Train/Serve/bench all drive it.

Design notes (TPU):
- matmuls in bfloat16 with fp32 accumulation (``preferred_element_type``),
  params kept fp32 by default (master weights), cast per-step.
- attention = ops.flash_attention (pallas on TPU) or ops.ring_attention
  when the sequence axis is sharded.
- ``jax.checkpoint`` per block to trade FLOPs for HBM (long context).
- rotary embeddings computed on the fly (no cached tables → no host
  transfers, fuses into the kernel).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention, gqa_expand
from ray_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Hyperparameters for the Llama family (reference parity target:
    the Llama-2-7B LoRA fine-tune from BASELINE.md)."""

    vocab_size: int = 32000
    hidden: int = 4096
    mlp_hidden: int = 11008
    layers: int = 32
    heads: int = 32
    kv_heads: int = 32
    head_dim: Optional[int] = None  # default hidden // heads
    max_seq: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32  # master weights
    remat: bool = True  # jax.checkpoint each block
    lora_rank: int = 0  # 0 = dense training; >0 = LoRA adapters on attn+mlp
    lora_alpha: float = 16.0
    # Mixture-of-experts (0 = dense MLP). Experts shard over the "expert"
    # mesh axis (EP); routing is top-k token-choice with capacity drop —
    # the Mixtral/Switch recipe expressed as dense einsums so GSPMD can
    # partition on the expert dim (no gather/scatter on the hot path).
    num_experts: int = 0
    experts_per_token: int = 2
    capacity_factor: float = 1.25

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden // self.heads

    def flops_per_token(self) -> float:
        """Approx forward+backward FLOPs/token (6*N + attention), for MFU."""
        n_params = self.num_params()
        attn = 12 * self.layers * self.hidden * self.max_seq  # rough
        return 6 * n_params + attn

    def num_params(self) -> int:
        h, m, l, v = self.hidden, self.mlp_hidden, self.layers, self.vocab_size
        hd, nh, nkv = self.hd, self.heads, self.kv_heads
        mlp = 3 * h * m
        if self.num_experts:
            mlp = self.num_experts * 3 * h * m + h * self.num_experts  # + router
        per_layer = h * (nh * hd) + 2 * h * (nkv * hd) + (nh * hd) * h + mlp + 2 * h
        emb = v * h * (1 if self.tie_embeddings else 2)
        return l * per_layer + emb + h


# Presets. llama2_7b mirrors the reference north-star target
# (BASELINE.md "Train Llama-2-7B LoRA ... v5e-64").
PRESETS: Dict[str, TransformerConfig] = {
    "debug": TransformerConfig(
        vocab_size=512, hidden=128, mlp_hidden=352, layers=2, heads=4,
        kv_heads=2, max_seq=128, remat=False,
    ),
    "tiny": TransformerConfig(
        vocab_size=2048, hidden=256, mlp_hidden=704, layers=4, heads=8,
        kv_heads=4, max_seq=512,
    ),
    "llama2_7b": TransformerConfig(),
    "llama2_7b_lora": TransformerConfig(lora_rank=16),
    "llama3_8b": TransformerConfig(
        vocab_size=128256, hidden=4096, mlp_hidden=14336, layers=32,
        heads=32, kv_heads=8, max_seq=8192, rope_theta=500000.0,
    ),
    # Mixtral-8x7B-shaped MoE (EP flagship)
    "mixtral_8x7b": TransformerConfig(
        vocab_size=32000, hidden=4096, mlp_hidden=14336, layers=32,
        heads=32, kv_heads=8, max_seq=8192, rope_theta=1e6,
        num_experts=8, experts_per_token=2,
    ),
    "moe_debug": TransformerConfig(
        vocab_size=512, hidden=128, mlp_hidden=256, layers=2, heads=4,
        kv_heads=2, max_seq=128, remat=False, num_experts=4,
        experts_per_token=2,
    ),
}


def config(name_or_cfg, **overrides) -> TransformerConfig:
    cfg = PRESETS[name_or_cfg] if isinstance(name_or_cfg, str) else name_or_cfg
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


# ---------------------------------------------------------------------------
# Parameter init + logical axes
# ---------------------------------------------------------------------------

def _dense_init(key, shape, dtype, fan_in):
    return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dtype)


def init_params(cfg: TransformerConfig, key: jax.Array) -> Params:
    """Initialize the parameter pytree. Layer params are STACKED on a
    leading ``layers`` dim so the forward is one ``lax.scan`` — one XLA
    while-loop body compiled once, not ``layers`` inlined copies (compile
    time and HBM win on TPU)."""
    h, m, v, l = cfg.hidden, cfg.mlp_hidden, cfg.vocab_size, cfg.layers
    hd, nh, nkv = cfg.hd, cfg.heads, cfg.kv_heads
    pd = cfg.param_dtype
    keys = jax.random.split(key, 13)

    def stack(k, shape, fan_in):
        ks = jax.random.split(k, l)
        return jnp.stack([_dense_init(ks[i], shape, pd, fan_in) for i in range(l)])

    blocks: Params = {
        "wq": stack(keys[1], (h, nh, hd), h),
        "wk": stack(keys[2], (h, nkv, hd), h),
        "wv": stack(keys[3], (h, nkv, hd), h),
        "wo": stack(keys[4], (nh, hd, h), nh * hd),
        "ln_attn": jnp.ones((l, h), pd),
        "ln_mlp": jnp.ones((l, h), pd),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        blocks["router"] = stack(keys[5], (h, e), h)
        blocks["wi_gate"] = stack(keys[6], (e, h, m), h)
        blocks["wi_up"] = stack(keys[7], (e, h, m), h)
        blocks["wo_mlp"] = stack(keys[8], (e, m, h), m)
    else:
        blocks["wi_gate"] = stack(keys[5], (h, m), h)
        blocks["wi_up"] = stack(keys[6], (h, m), h)
        blocks["wo_mlp"] = stack(keys[7], (m, h), m)
    params: Params = {
        "embed": _dense_init(keys[0], (v, h), pd, h),  # scaled like output
        "blocks": blocks,
        "ln_f": jnp.ones((h,), pd),
    }
    if not cfg.tie_embeddings:
        # keys[12]: own key — keys[8] seeds the MoE wo_mlp stack, and
        # sharing it would correlate the two inits (advisor finding, r1)
        params["unembed"] = _dense_init(keys[12], (h, v), pd, h)
    if cfg.lora_rank:
        r = cfg.lora_rank
        def lz(shape):  # LoRA B starts at zero
            return jnp.zeros(shape, pd)
        params["lora"] = {
            "wq_a": stack(keys[9], (h, r), h), "wq_b": jnp.zeros((l, r, nh * hd), pd),
            "wv_a": stack(keys[10], (h, r), h), "wv_b": jnp.zeros((l, r, nkv * hd), pd),
            "wi_a": stack(keys[11], (h, r), h), "wi_b": lz((l, r, m)),
        }
    return params


def param_axes(cfg: TransformerConfig) -> Params:
    """Pytree of logical-axis tuples mirroring init_params output.
    Feed to parallel.sharding.tree_shardings(mesh, ...) for NamedShardings."""
    block_axes: Params = {
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "ln_attn": ("layers", "norm"),
        "ln_mlp": ("layers", "norm"),
    }
    if cfg.num_experts:
        block_axes.update({
            "router": ("layers", "embed", None),  # router stays replicated
            "wi_gate": ("layers", "expert", "embed", "mlp"),
            "wi_up": ("layers", "expert", "embed", "mlp"),
            "wo_mlp": ("layers", "expert", "mlp", "embed"),
        })
    else:
        block_axes.update({
            "wi_gate": ("layers", "embed", "mlp"),
            "wi_up": ("layers", "embed", "mlp"),
            "wo_mlp": ("layers", "mlp", "embed"),
        })
    axes: Params = {
        "embed": ("vocab", "embed"),
        "blocks": block_axes,
        "ln_f": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["unembed"] = ("embed", "vocab")
    if cfg.lora_rank:
        axes["lora"] = {
            "wq_a": ("layers", "embed", "lora_rank"), "wq_b": ("layers", "lora_rank", "heads"),
            "wv_a": ("layers", "embed", "lora_rank"), "wv_b": ("layers", "lora_rank", "kv_heads"),
            "wi_a": ("layers", "embed", "lora_rank"), "wi_b": ("layers", "lora_rank", "mlp"),
        }
    return axes


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x [B,S,H,D], positions [B,S] or [S]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    if positions.ndim == 1:
        positions = positions[None, :]
    ang = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos, sin = jnp.cos(ang)[:, :, None, :], jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _lora_delta(x, a, b, scale):
    return jnp.einsum("bsh,hr->bsr", x, a.astype(x.dtype)) @ b.astype(x.dtype) * scale


def _moe_mlp(cfg: TransformerConfig, y, p):
    """Top-k token-choice MoE with capacity drop (GShard/Mixtral recipe).

    Dense-dispatch formulation: routing becomes one-hot dispatch/combine
    tensors and the expert FFN is a single batched einsum with the expert
    dim sharded over the "expert" mesh axis — GSPMD inserts the
    all-to-alls; no dynamic gather on the TPU hot path.
    """
    b, s, h = y.shape
    e, k = cfg.num_experts, cfg.experts_per_token
    t = b * s
    x = y.reshape(t, h)

    logits = jnp.einsum("th,he->te", x, p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)  # [T,k]
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    # capacity per expert; first-choice assignments get priority by
    # ordering the flattened (choice-major) token stream
    cap = max(4, int(cfg.capacity_factor * t * k / e))
    oh = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)          # [T,k,E]
    ohf = oh.transpose(1, 0, 2).reshape(k * t, e)                # choice-major
    pos = (jnp.cumsum(ohf, axis=0) - 1.0) * ohf                  # slot per entry
    keep = (pos < cap) & (ohf > 0)
    slot = pos.sum(-1).astype(jnp.int32)                         # [kT]
    slot_oh = jax.nn.one_hot(slot, cap, dtype=jnp.float32)       # [kT,C]
    dispatch = ohf[:, :, None] * slot_oh[:, None, :] * keep.any(-1)[:, None, None]
    gates_f = gate_vals.T.reshape(k * t)                         # choice-major
    combine = dispatch * gates_f[:, None, None]

    xk = jnp.tile(x, (k, 1)).astype(jnp.float32)                 # [kT,h]
    expert_in = jnp.einsum("pec,ph->ech", dispatch, xk).astype(y.dtype)
    expert_in = constrain(expert_in, ("expert", None, "embed"))
    gate = jnp.einsum("ech,ehm->ecm", expert_in, p["wi_gate"].astype(y.dtype))
    up = jnp.einsum("ech,ehm->ecm", expert_in, p["wi_up"].astype(y.dtype))
    act = jax.nn.silu(gate) * up
    act = constrain(act, ("expert", None, "mlp"))
    out_e = jnp.einsum("ecm,emh->ech", act, p["wo_mlp"].astype(y.dtype))
    yk = jnp.einsum("pec,ech->ph", combine.astype(y.dtype), out_e)  # [kT,h]
    out = yk.reshape(k, t, h).sum(0).reshape(b, s, h)
    return out


def _block(cfg: TransformerConfig, x, layer_params, lora_params, positions,
           attn_fn):
    """One decoder block. x [B,S,H_emb] in compute dtype."""
    p = layer_params
    nh, nkv, hd = cfg.heads, cfg.kv_heads, cfg.hd
    b, s, h = x.shape
    scale = cfg.lora_alpha / cfg.lora_rank if cfg.lora_rank else 0.0

    y = _rms_norm(x, p["ln_attn"], cfg.norm_eps)
    q = jnp.einsum("bsh,hnd->bsnd", y, p["wq"].astype(y.dtype))
    k = jnp.einsum("bsh,hnd->bsnd", y, p["wk"].astype(y.dtype))
    v = jnp.einsum("bsh,hnd->bsnd", y, p["wv"].astype(y.dtype))
    if lora_params is not None:
        q = q + _lora_delta(y, lora_params["wq_a"], lora_params["wq_b"], scale).reshape(b, s, nh, hd)
        v = v + _lora_delta(y, lora_params["wv_a"], lora_params["wv_b"], scale).reshape(b, s, nkv, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    q = constrain(q, ("batch", "seq", "heads", None))
    attn = attn_fn(q, k, v)
    attn = jnp.einsum("bsnd,ndh->bsh", attn, p["wo"].astype(attn.dtype))
    x = x + constrain(attn, ("batch", "seq", "embed"))

    y = _rms_norm(x, p["ln_mlp"], cfg.norm_eps)
    if cfg.num_experts:
        out = _moe_mlp(cfg, y, p)
    else:
        gate = jnp.einsum("bsh,hm->bsm", y, p["wi_gate"].astype(y.dtype))
        up = jnp.einsum("bsh,hm->bsm", y, p["wi_up"].astype(y.dtype))
        if lora_params is not None:
            gate = gate + _lora_delta(y, lora_params["wi_a"], lora_params["wi_b"], scale)
        act = jax.nn.silu(gate) * up
        act = constrain(act, ("batch", "seq", "mlp"))
        out = jnp.einsum("bsm,mh->bsh", act, p["wo_mlp"].astype(act.dtype))
    return x + constrain(out, ("batch", "seq", "embed"))


def _default_attn(cfg: TransformerConfig):
    def attn(q, k, v):
        k, v = gqa_expand(k, v, cfg.heads)
        return flash_attention(q, k, v, causal=True)
    return attn


def forward(cfg: TransformerConfig, params: Params, tokens: jax.Array,
            positions: Optional[jax.Array] = None,
            attn_fn=None, mesh=None,
            num_microbatches: Optional[int] = None) -> jax.Array:
    """tokens [B,S] int32 → logits [B,S,V] (compute dtype).

    ``attn_fn(q,k,v)->o`` overrides attention — ring_attention for
    sequence parallelism is passed in by the train-step builder.
    ``mesh`` with a "stage" axis > 1 switches the layer stack to
    pipeline parallelism (ops/pipeline.py) with ``num_microbatches``.
    """
    if positions is None:
        positions = jnp.arange(tokens.shape[1])
    attn_fn = attn_fn or _default_attn(cfg)
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = constrain(x, ("batch", "seq", "embed"))

    blocks, lora = params["blocks"], params.get("lora")

    def body_at(pos):
        def body(x, layer):
            lp = layer["p"]
            lo = layer.get("l")
            out = _block(cfg, x, lp, lo, pos, attn_fn)
            return out, None
        return body

    body = body_at(positions)

    layer_tree = {"p": blocks}
    if lora is not None:
        layer_tree["l"] = lora
    def _remat(fn):
        # Full per-block remat: the backward recomputes each block from its
        # input. Selective policies (saving attention outputs) don't help
        # here — flash_attention's custom_vjp needs its lse residual, which
        # only the re-run forward kernel produces.
        return jax.checkpoint(fn) if cfg.remat else fn

    n_stage = mesh.shape.get("stage", 1) if mesh is not None else 1
    if n_stage > 1:
        from ray_tpu.ops.pipeline import pipelined_layers

        n_seq = mesh.shape.get("sequence", 1)
        seq_axis = "sequence" if n_seq > 1 else None

        def apply_stage(layers_local, h, pos_local):
            h, _ = lax.scan(_remat(body_at(pos_local)), h, layers_local)
            return h

        x = pipelined_layers(
            mesh, apply_stage, layer_tree, x, positions,
            num_microbatches or 2 * n_stage,
            seq_axis=seq_axis,
        )
    else:
        x, _ = lax.scan(_remat(body), x, layer_tree)

    x = _rms_norm(x, params["ln_f"], cfg.norm_eps)
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsh,hv->bsv", x, unembed.astype(x.dtype))
    return constrain(logits, ("batch", "seq", "vocab"))


def loss_fn(cfg: TransformerConfig, params: Params, batch: Dict[str, jax.Array],
            attn_fn=None, mesh=None,
            num_microbatches: Optional[int] = None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross-entropy. batch: tokens [B,S], optional loss_mask [B,S].
    Returns (loss, metrics)."""
    tokens = batch["tokens"]
    # Forward over the FULL sequence (sequence-parallel shards must keep
    # S divisible by the mesh axis); shift at the logits instead.
    logits = forward(cfg, params, tokens, attn_fn=attn_fn, mesh=mesh,
                     num_microbatches=num_microbatches)[:, :-1]
    targets = tokens[:, 1:]
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    mask = batch.get("loss_mask")
    if mask is not None:
        mask = mask[:, 1:].astype(jnp.float32)
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / denom
    else:
        denom = jnp.asarray(nll.size, jnp.float32)
        loss = nll.mean()
    acc = (logits.argmax(-1) == targets).astype(jnp.float32)
    if mask is not None:
        acc = (acc * mask).sum() / denom
    else:
        acc = acc.mean()
    return loss, {"loss": loss, "accuracy": acc, "tokens": denom}


def trainable_mask(cfg: TransformerConfig, params: Params) -> Params:
    """True where a param trains: everything for dense, only adapters for
    LoRA (the reference's LoRA target trains adapters only)."""
    if not cfg.lora_rank:
        return jax.tree.map(lambda _: True, params)
    return jax.tree_util.tree_map_with_path(
        lambda path, _: any(getattr(k, "key", None) == "lora" for k in path),
        params,
    )
